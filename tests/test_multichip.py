"""Multichip sharded serving tests (the promoted `part`-axis path).

What the MULTICHIP_r01–r05 dry runs never proved, proven here on the
8-device virtual CPU mesh (conftest.py):

- sharded-vs-single-device byte identity under LIVE delta overlays and
  mixed Range/Count batches, across both the jnp and pallas-interpret
  kernels, including partitions > devices (P//N partitions per device);
- per-scan host transfer bounded by visible rows (never the [P, N] mask or
  a replicated key gather) — the transfer meter backing kblint KB111;
- delta-overlay publish re-uploads ONLY dirty device shards, including
  under concurrent writers;
- kb_mirror_bytes{device=} per-shard HBM accounting on /metrics;
- the --mesh-part/--scan-partitions serving-front flags and the workload
  spec's mesh knobs validate correctly.
"""

import threading

import numpy as np
import pytest

from kubebrain_tpu.backend import Backend, BackendConfig
from kubebrain_tpu.parallel.mesh import make_mesh
from kubebrain_tpu.storage import new_storage
from kubebrain_tpu.storage.tpu.engine import (
    TRANSFER_METER,
    TpuKvStorage,
    TpuScanner,
    _pow2_bucket,
)


def make_backend(ndev, partitions=0, kernel="jnp", merge_threshold=8):
    mesh = make_mesh(n_devices=ndev)
    store = TpuKvStorage(new_storage("memkv"), mesh=mesh,
                         partitions=partitions)
    b = Backend(store, BackendConfig(event_ring_capacity=8192))
    b.scanner._host_limit_threshold = 0  # always the device path
    b.scanner._merge_threshold = merge_threshold
    # pin the kernel explicitly (ambient KB_USE_PALLAS / a TPU backend must
    # not flip the differential under test)
    b.scanner._scan_kernel = kernel
    b.scanner._kernel_mesh = mesh if kernel != "jnp" else None
    return b


def fp_result(res):
    return [(kv.key, kv.value, kv.revision) for kv in res.kvs] + \
        [(res.revision, res.count, res.more)]


def fp_batch(out):
    fps = []
    for r in out:
        assert not isinstance(r, BaseException), r
        fps.append(r if isinstance(r, tuple) else fp_result(r))
    return fps


NSR = [(b"/registry/pods/ns-%02d/" % ns, b"/registry/pods/ns-%02d0" % ns)
       for ns in range(8)]


@pytest.mark.parametrize("kernel", ["jnp", "pallas_interpret"])
@pytest.mark.parametrize("ndev,parts", [(8, 0), (4, 8)])
def test_sharded_vs_single_byte_identity_live_overlays(kernel, ndev, parts):
    """Random mixed workload on a 1-device engine vs a sharded one (one
    partition per device AND two partitions per device); every read —
    head + snapshot Ranges, Counts, mixed list_batch — must agree byte for
    byte while the sharded engine still holds a LIVE delta overlay (its
    merge threshold is effectively infinite)."""
    rng = np.random.RandomState(11)
    ref = make_backend(1, kernel="jnp", merge_threshold=4)
    shard = make_backend(ndev, partitions=parts, kernel=kernel,
                         merge_threshold=10**9)  # delta overlay stays live
    try:
        live: dict[bytes, int] = {}
        checkpoints = []
        for step in range(160):
            ns = rng.randint(8)
            k = b"/registry/pods/ns-%02d/p-%04d" % (ns, rng.randint(40))
            op = rng.rand()
            if k not in live:
                action = "create"
            elif op < 0.45:
                action = "recreate"
            elif op < 0.85:
                action = "update"
            else:
                action = "delete"
            for be in (ref, shard):
                if action == "create":
                    r = be.create(k, b"v%03d" % step)
                elif action == "recreate":
                    be.delete(k)
                    r = be.create(k, b"v%03d" % step)
                elif action == "update":
                    r = be.update(k, b"u%03d" % step, live[k])
                else:
                    r, _ = be.delete(k)
            if action == "delete":
                live.pop(k)
            else:
                live[k] = r
            if step % 40 == 17:
                checkpoints.append(ref.current_revision())

            if step % 10 == 3:  # reads interleaved with the writes
                s, e = NSR[ns]
                assert fp_result(ref.list_(s, e)) == fp_result(shard.list_(s, e))
                assert ref.count(s, e) == shard.count(s, e)

        # the sharded engine must still be overlay-serving (nothing merged)
        assert len(shard.scanner._delta) > 0
        assert shard.scanner._mirror.partitions == (parts or ndev)

        # full + per-ns reads at head and at historical snapshots
        assert fp_result(ref.list_(b"/registry/", b"/registry0")) == \
            fp_result(shard.list_(b"/registry/", b"/registry0"))
        for rev in checkpoints:
            for s, e in NSR[:4]:
                assert fp_result(ref.list_(s, e, revision=rev)) == \
                    fp_result(shard.list_(s, e, revision=rev))

        # mixed Range/Count batches through the batch executor (the
        # scheduler's query-batched path): one device dispatch on the
        # sharded engine, byte-identical demux
        queries = []
        for i, (s, e) in enumerate(NSR):
            if i % 3 == 2:
                queries.append(("count", s, e, 0))
            else:
                queries.append(("list", s, e, 0, 0))
        assert fp_batch(ref.list_batch(queries)) == \
            fp_batch(shard.list_batch(queries))
    finally:
        for be in (ref, shard):
            store = be.store
            be.close()
            store.close()


def _scanner_over_rows(n_rows, ndev=8, partitions=0):
    """A published TpuScanner over ``n_rows`` single-revision keys written
    straight into the host engine (bulk batches — no Backend overhead)."""
    from kubebrain_tpu import coder

    store = TpuKvStorage(new_storage("memkv"),
                         mesh=make_mesh(n_devices=ndev),
                         partitions=partitions)
    rev = 0
    for base in range(0, n_rows, 2000):
        b = store.begin_batch_write()
        for i in range(base, min(base + 2000, n_rows)):
            rev += 1
            b.put(coder.encode_object_key(b"/registry/pods/p%07d" % i, rev),
                  b"v" * 16)
        b.commit()
    scanner = store.make_scanner(get_compact_revision=lambda _s: 0)
    scanner._host_limit_threshold = 0
    scanner.publish()
    return store, scanner, rev


def test_host_transfer_budget_bounded_by_visible_rows():
    """Per-scan device→host bytes scale with VISIBLE rows, never with the
    dataset: a 64-row window over a 16k-row mirror must move orders of
    magnitude less than the [P, N] mask (let alone the packed keys), and
    the bound is the documented P·pow2(max-per-shard)·8B index block."""
    P = 8
    n_rows = 16_384
    store, scanner, head = _scanner_over_rows(n_rows, ndev=P)
    try:
        n_pad = scanner._mirror.keys_host.shape[1]
        mask_bytes = P * n_pad            # bool [P, N] — the forbidden pull
        # the unthinkable pull, at RAW key width: the prefix-encoded mirror
        # shrinks the stored column ~6x, which must not relax the absolute
        # index-block budget asserted below
        key_bytes = P * n_pad * scanner._mirror.raw_key_width

        def measured(fn):
            fn()  # warm: compile + bucket shapes off the meter's budget
            b0, _ = TRANSFER_METER.snapshot()
            out = fn()
            b1, _ = TRANSFER_METER.snapshot()
            return out, b1 - b0

        # narrow window: 64 visible rows
        s, e = b"/registry/pods/p0000000", b"/registry/pods/p0000064"
        (kvs, _more), cost = measured(lambda: scanner.range_(s, e, head))
        visible = len(kvs)
        assert visible == 64
        budget = P * _pow2_bucket(visible, n_pad) * 8 + 16 * P + 64
        assert cost <= budget, (cost, budget)
        assert cost < mask_bytes, (cost, mask_bytes)
        assert cost < key_bytes // 100

        # full scan: the transfer may be O(visible)·8B, still never the keys
        (kvs_all, _), cost_all = measured(
            lambda: scanner.range_(b"/registry/pods/", b"/registry/pods0",
                                   head))
        assert len(kvs_all) == n_rows
        per_shard = -(-n_rows // P)
        assert cost_all <= P * _pow2_bucket(per_shard, n_pad) * 8 + 16 * P + 64
        assert cost_all < key_bytes // 10

        # batched path (mixed Range/Count): same O(visible) discipline —
        # Count rows never cross the wire
        def batched():
            return scanner.scan_batch([
                ("range", s, e, head, 0),
                ("count", b"/registry/pods/", b"/registry/pods0", head),
                ("range", b"/registry/pods/p0001000",
                 b"/registry/pods/p0001032", head, 0),
            ])
        out, cost_b = measured(batched)
        assert out[1] == n_rows and len(out[0][0]) == 64 and len(out[2][0]) == 32
        qpad = 4  # 3 queries pow2-padded
        budget_b = qpad * P * _pow2_bucket(64, n_pad) * 8 + qpad * P * 8 + 64
        assert cost_b <= budget_b, (cost_b, budget_b)
        assert cost_b < mask_bytes
    finally:
        store.close()


def test_dirty_shard_only_republish_on_mesh():
    """Delta merges re-upload ONLY the device shards holding dirty
    partitions: clean shards must reuse the previous mirror's device
    buffers (buffer-pointer identity), including with concurrent writers
    hammering one namespace while readers scan."""
    P = 8
    store, scanner, head = _scanner_over_rows(4096, ndev=P)
    try:
        scanner._merge_threshold = 1  # every publish merges the delta
        mirror1 = scanner._mirror
        shards1 = list(mirror1.keys_dev.addressable_shards)
        if not hasattr(shards1[0].data, "unsafe_buffer_pointer"):
            pytest.skip("jax.Array.unsafe_buffer_pointer unavailable")
        ptrs1 = {str(s.device): s.data.unsafe_buffer_pointer()
                 for s in shards1}

        # dirty exactly one partition: keys above every existing key land
        # in the LAST partition
        from kubebrain_tpu import coder

        b = store.begin_batch_write()
        for i in range(16):
            b.put(coder.encode_object_key(b"/registry/pods/zzz-%03d" % i,
                                          head + 1 + i), b"w")
        b.commit()
        scanner.publish()
        mirror2 = scanner._mirror
        assert mirror2 is not mirror1
        ptrs2 = {str(s.device): s.data.unsafe_buffer_pointer()
                 for s in mirror2.keys_dev.addressable_shards}
        changed = [d for d in ptrs1 if ptrs1[d] != ptrs2[d]]
        assert len(changed) == 1, (
            f"expected exactly the last partition's shard re-uploaded, "
            f"got {changed}")

        # concurrent writers + readers: correctness holds and the next
        # publish still only re-uploads the written-to shards
        stop = threading.Event()
        errors: list = []

        def writer():
            # bounded + paced: the tail partition has ~500 rows of padded
            # headroom, and overflowing it forces the full-rebuild fallback
            # (a different, legitimate path — not the one under test)
            import time as _time

            for i in range(120):
                if stop.is_set():
                    return
                bw = store.begin_batch_write()
                bw.put(coder.encode_object_key(
                    b"/registry/pods/zzz-live-%04d" % i,
                    head + 100 + i), b"c")
                bw.commit()
                _time.sleep(0.002)

        def reader():
            try:
                while not stop.is_set():
                    kvs, _ = scanner.range_(b"/registry/pods/p0000000",
                                            b"/registry/pods/p0000064", head)
                    assert len(kvs) == 64
            except Exception as e:  # surfaced to the main thread
                errors.append(e)

        threads = [threading.Thread(target=writer),
                   threading.Thread(target=reader)]
        for t in threads:
            t.start()
        import time as _time

        for _ in range(5):
            _time.sleep(0.05)
            scanner.publish()
        stop.set()
        for t in threads:
            t.join(10.0)
        assert not errors, errors

        mirror3 = scanner._mirror
        ptrs3 = {str(s.device): s.data.unsafe_buffer_pointer()
                 for s in mirror3.keys_dev.addressable_shards}
        unchanged = [d for d in ptrs2 if ptrs2.get(d) == ptrs3.get(d)]
        # every concurrent write landed in the tail partition; at least the
        # low partitions' buffers must have survived every merge untouched
        assert len(unchanged) >= P - 2, unchanged
    finally:
        store.close()


def test_partitions_multiple_of_devices_enforced():
    with pytest.raises(ValueError, match="multiple of the mesh"):
        TpuScanner(new_storage("memkv"), get_compact_revision=lambda _s: 0,
                   mesh=make_mesh(n_devices=4), partitions=6)


def test_mirror_bytes_gauge_per_device():
    """kb_mirror_bytes{device=}: one scrape-time gauge per mesh device,
    each bounded well below the whole-mirror total — the observable form
    of 'per-chip HBM bounds the dataset, not the whole mirror'."""
    prom = pytest.importorskip("prometheus_client")  # noqa: F841
    from kubebrain_tpu.metrics import new_metrics

    store, scanner, _head = _scanner_over_rows(4096, ndev=8)
    try:
        metrics = new_metrics("")
        scanner.register_metrics(metrics)
        _ctype, body = metrics.http_handler()()
        values = {}
        for line in body.decode().splitlines():
            if line.startswith("kb_mirror_bytes{"):
                label, val = line.rsplit(" ", 1)
                values[label] = float(val)
        assert len(values) == 8, values
        total = sum(values.values())
        assert total > 0
        for label, v in values.items():
            assert v > 0, (label, values)
            assert v <= total * 0.5, (label, values)
    finally:
        store.close()


def test_cli_mesh_flags_validate():
    from kubebrain_tpu.cli import build_parser, validate_args

    p = build_parser()
    ok = p.parse_args(["--storage", "tpu", "--mesh-part", "4",
                       "--scan-partitions", "8"])
    validate_args(ok)

    with pytest.raises(SystemExit):  # flags require the tpu engine
        validate_args(p.parse_args(["--mesh-part", "4"]))
    with pytest.raises(SystemExit):  # P must be a multiple of N
        validate_args(p.parse_args(
            ["--storage", "tpu", "--mesh-part", "4",
             "--scan-partitions", "6"]))
    with pytest.raises(SystemExit):
        validate_args(p.parse_args(["--storage", "tpu", "--mesh-part", "-1"]))


def test_workload_spec_mesh_knobs_validate():
    from kubebrain_tpu.workload.spec import WorkloadSpec

    WorkloadSpec.for_smoke(4, storage="tpu", mesh_part=2,
                           scan_partitions=4).validate()
    with pytest.raises(ValueError, match="storage='tpu'"):
        WorkloadSpec.for_smoke(4, mesh_part=2).validate()
    with pytest.raises(ValueError, match=">= 0"):
        WorkloadSpec.for_smoke(4, storage="tpu", mesh_part=-1).validate()
    with pytest.raises(ValueError, match="multiple of mesh_part"):
        # the cli boot check, mirrored: fail at validate, not at spawn
        WorkloadSpec.for_smoke(4, storage="tpu", mesh_part=4,
                               scan_partitions=6).validate()
