"""Multi-HOST data plane: 2 separate processes (2 virtual devices each) form
one 4-device global mesh via jax.distributed (Gloo collectives standing in
for ICI/DCN) and run the full sharded scan/compact/fan-out step — the
SURVEY §2.10 scale model executed for real, not just dry-run."""

import os
import re
import socket
import subprocess
import sys

import jax
import pytest

# the worker runs parallel/step.py, whose data-plane step is built on the
# stable jax.shard_map alias; jax 0.4.37 (this container) only ships the
# experimental variant, so the subprocess would die at import time
pytestmark = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="jax.shard_map unavailable in this jax version (0.4.37 predates "
           "the stable alias; parallel/step.py needs it)",
)


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_global_mesh_step():
    port = str(free_port())
    worker = os.path.join(os.path.dirname(__file__), "mh_worker.py")
    env = {**os.environ}
    env.pop("PALLAS_AXON_POOL_IPS", None)  # keep the TPU tunnel out of it
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )
        for pid in range(2)
    ]
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=150)
        outputs.append(out.decode())
        assert p.returncode == 0, out.decode()[-2000:]
    totals = []
    for out in outputs:
        m = re.search(r"MHRESULT pid=(\d) devices=(\d+) total=(\d+)", out)
        assert m, out[-2000:]
        assert m.group(2) == "4"  # both processes see the global 4-device mesh
        totals.append(int(m.group(3)))
    assert totals[0] == totals[1] > 0  # psum agreed across hosts
