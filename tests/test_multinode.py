"""Multi-node cluster tests: 3 stateless nodes over one shared engine.

Reference analogue: the in-process mock-TiKV multi-node tests (SURVEY §4
mechanism #1) + the master/slave replica model (README.md:21-24): the leader
owns writes and the watch pipeline; followers sync the read revision from
the leader's /status and (with the proxy) forward writes; killing the leader
hands leadership over with monotonic revisions.
"""

import socket
import time

import grpc
import pytest

from kubebrain_tpu.backend import Backend, BackendConfig
from kubebrain_tpu.endpoint import Endpoint, EndpointConfig
from kubebrain_tpu.metrics import NoopMetrics
from kubebrain_tpu.proto import rpc_pb2
from kubebrain_tpu.server import Server
from kubebrain_tpu.server.service import PeerService
from kubebrain_tpu.storage import new_storage

from test_etcd_server import EtcdClient, free_port


class Node:
    def __init__(self, store, enable_proxy=True):
        self.client_port = free_port()
        self.peer_port = free_port()
        self.info_port = free_port()
        self.identity = f"127.0.0.1:{self.peer_port}"
        self.backend = Backend(store, BackendConfig(event_ring_capacity=8192,
                                                    watch_cache_capacity=8192))
        self.peers = PeerService(
            self.backend, self.identity, self.client_port, enable_proxy=enable_proxy
        )
        # fast elections for tests
        self.peers.election._lease = 0.6
        self.peers.election._renew = 0.1
        self.peers.election._retry = 0.05
        self.server = Server(self.backend, self.peers, NoopMetrics(), self.identity)
        self.endpoint = Endpoint(self.server, NoopMetrics(), EndpointConfig(
            host="127.0.0.1", client_port=self.client_port,
            peer_port=self.peer_port, info_port=self.info_port,
        ))
        self.endpoint.run()
        self.client = EtcdClient(f"127.0.0.1:{self.client_port}")

    def close(self):
        self.client.close()
        self.endpoint.close()
        self.backend.close()


@pytest.fixture
def cluster():
    store = new_storage("memkv")
    nodes = [Node(store) for _ in range(3)]
    deadline = time.time() + 10
    while time.time() < deadline:
        if any(n.peers.is_leader() for n in nodes):
            break
        time.sleep(0.05)
    leaders = [n for n in nodes if n.peers.is_leader()]
    assert len(leaders) == 1, "expected exactly one leader"
    yield nodes, leaders[0], store
    for n in nodes:
        n.close()
    store.close()


def test_leader_writes_follower_reads(cluster):
    nodes, leader, _ = cluster
    followers = [n for n in nodes if n is not leader]
    resp = leader.client.create(b"/registry/pods/a", b"v1")
    assert resp.succeeded
    rev = resp.responses[0].response_put.header.revision
    # follower read syncs revision from the leader's /status over HTTP
    f = followers[0]
    r = f.client.range_(rpc_pb2.RangeRequest(key=b"/registry/pods/", range_end=b"/registry/pods0"))
    assert r.count == 1 and r.kvs[0].value == b"v1"
    assert f.backend.current_revision() >= rev


def test_follower_write_forwarded_via_proxy(cluster):
    nodes, leader, _ = cluster
    follower = next(n for n in nodes if n is not leader)
    resp = follower.client.create(b"/registry/pods/via-follower", b"v1")
    assert resp.succeeded  # proxied to the leader transparently
    r = leader.client.range_(rpc_pb2.RangeRequest(key=b"/registry/pods/via-follower"))
    assert r.count == 1


def test_follower_watch_forwarded(cluster):
    import queue as q

    nodes, leader, _ = cluster
    follower = next(n for n in nodes if n is not leader)
    requests: q.Queue = q.Queue()
    responses = follower.client.watch(iter(requests.get, None))
    req = rpc_pb2.WatchRequest()
    req.create_request.key = b"/registry/fw/"
    req.create_request.range_end = b"/registry/fw0"
    requests.put(req)
    assert next(responses).created
    leader.client.create(b"/registry/fw/x", b"v")
    wr = next(responses)
    assert wr.events[0].kv.key == b"/registry/fw/x"
    requests.put(None)


def test_leader_failover_monotonic_revisions(cluster):
    nodes, leader, _ = cluster
    resp = leader.client.create(b"/registry/pods/before", b"v")
    rev_before = resp.responses[0].response_put.header.revision

    leader.close()
    survivors = [n for n in nodes if n is not leader]
    deadline = time.time() + 10
    new_leader = None
    while time.time() < deadline and new_leader is None:
        for n in survivors:
            if n.peers.is_leader():
                new_leader = n
                break
        time.sleep(0.05)
    assert new_leader is not None, "no failover within 10s"

    resp = new_leader.client.create(b"/registry/pods/after", b"v2")
    assert resp.succeeded
    rev_after = resp.responses[0].response_put.header.revision
    assert rev_after > rev_before  # revisions never go backwards across terms
    # old data still visible through the new leader
    r = new_leader.client.range_(
        rpc_pb2.RangeRequest(key=b"/registry/pods/", range_end=b"/registry/pods0")
    )
    keys = [kv.key for kv in r.kvs]
    assert b"/registry/pods/before" in keys and b"/registry/pods/after" in keys
    nodes.remove(leader)  # already closed


def test_restart_resumes_revisions():
    """Single node restart over a persistent engine resumes the sequence."""
    store = new_storage("memkv")
    b1 = Backend(store, BackendConfig(event_ring_capacity=1024))
    r1 = b1.create(b"/k", b"v1")
    r2 = b1.update(b"/k", b"v2", r1)
    b1.close()
    b2 = Backend(store, BackendConfig(event_ring_capacity=1024))
    assert b2.current_revision() >= r2
    r3 = b2.create(b"/k2", b"v")
    assert r3 > r2
    assert b2.get(b"/k").value == b"v2"
    b2.close()
    store.close()


def test_leader_loss_resets_watch_pipeline():
    """Losing leadership drops every watcher (poison pills force clients to
    re-watch) — the observable contract of the reference's
    panic-on-leader-loss (leader.go:109-118)."""
    store = new_storage("memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=2048))
    wid, q = b.watch(b"/registry/")
    b.create(b"/registry/a", b"v")
    assert q.get(timeout=5) is not None
    b.reset_term()
    # the pill arrives (after any buffered events)
    saw_pill = False
    for _ in range(10):
        item = q.get(timeout=2)
        if item is None:
            saw_pill = True
            break
    assert saw_pill
    assert b.watcher_hub.watcher_count() == 0
    # pipeline remains usable: new watch + write still flows
    wid2, q2 = b.watch(b"/registry/")
    b.create(b"/registry/b", b"v2")
    batch = q2.get(timeout=5)
    assert batch and batch[0].key == b"/registry/b"
    b.close()
    store.close()


def test_follower_read_fails_without_leader():
    """Failure to sync the read revision fails the read (reference
    brain/read.go:128-130) — a follower must not serve stale data silently."""
    from kubebrain_tpu.server.service.revision import RevisionSyncError

    store = new_storage("memkv")
    # plant an unexpired lock record owned by an unreachable peer BEFORE the
    # node starts campaigning, so it stays a follower of a dead leader
    from kubebrain_tpu.backend.election import ResourceLock

    dead = ResourceLock(store, "10.255.255.1:19999",
                        meta={"client": "10.255.255.1:19998"})
    import time as _t

    dead.create(_t.time() + 3600)  # renewed far in the future
    node = Node(store)
    try:
        _t.sleep(0.3)
        assert not node.peers.is_leader()
        with pytest.raises(grpc.RpcError):
            node.client.range_(
                rpc_pb2.RangeRequest(key=b"/registry/", range_end=b"/registry0")
            )
    finally:
        node.close()
        store.close()
