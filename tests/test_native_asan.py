"""Sanitized native build smoke: `make -C native asan` must build, and the
smoke binary (linked against the ASan/UBSan libkbstore.so) must drive the
engine path clean — any sanitizer report fails the run."""

import os
import shutil
import subprocess

import pytest

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")


def _toolchain_available() -> bool:
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None or shutil.which("make") is None:
        return False
    # the sanitizer runtime may be missing even when g++ exists
    probe = subprocess.run(
        [cxx, "-fsanitize=address", "-x", "c++", "-", "-o", "/dev/null"],
        input=b"int main(){return 0;}", capture_output=True,
    )
    return probe.returncode == 0


pytestmark = pytest.mark.skipif(
    not _toolchain_available(), reason="C++ toolchain or ASan runtime unavailable"
)


def test_asan_build_and_smoke(tmp_path):
    build = subprocess.run(
        ["make", "-C", NATIVE_DIR, "asan"], capture_output=True, text=True
    )
    assert build.returncode == 0, build.stdout + build.stderr

    smoke = subprocess.run(
        [os.path.join(NATIVE_DIR, "kbstore_smoke_asan"), str(tmp_path / "wal")],
        capture_output=True, text=True,
        env={**os.environ, "ASAN_OPTIONS": "abort_on_error=1",
             "UBSAN_OPTIONS": "halt_on_error=1"},
    )
    assert smoke.returncode == 0, smoke.stdout + smoke.stderr
    assert "SMOKE OK" in smoke.stdout
    # the sanitized library is what the binary actually loaded
    maps = subprocess.run(
        ["ldd", os.path.join(NATIVE_DIR, "kbstore_smoke_asan")],
        capture_output=True, text=True,
    )
    assert "libkbstore_asan.so" in maps.stdout


@pytest.mark.slow
def test_tsan_build_and_smoke(tmp_path):
    build = subprocess.run(
        ["make", "-C", NATIVE_DIR, "tsan"], capture_output=True, text=True
    )
    assert build.returncode == 0, build.stdout + build.stderr
    smoke = subprocess.run(
        [os.path.join(NATIVE_DIR, "kbstore_smoke_tsan"), str(tmp_path / "wal")],
        capture_output=True, text=True,
        env={**os.environ, "TSAN_OPTIONS": "halt_on_error=1"},
    )
    assert smoke.returncode == 0, smoke.stdout + smoke.stderr
    assert "SMOKE OK" in smoke.stdout
