"""C++ native engine: same contract tests as memkv + backend semantics over
the native store (the reference runs one table-driven suite across engines,
backend_test.go:52-88)."""

import time

import pytest

from kubebrain_tpu.backend import Backend, BackendConfig, wait_for_revision
from kubebrain_tpu.storage import new_storage
from kubebrain_tpu.storage.errors import CASFailedError, KeyNotFoundError


@pytest.fixture
def store():
    s = new_storage("native")
    yield s
    s.close()


def put(store, key, value, ttl=0):
    b = store.begin_batch_write()
    b.put(key, value, ttl)
    b.commit()


def test_crud(store):
    with pytest.raises(KeyNotFoundError):
        store.get(b"k")
    put(store, b"k", b"v1")
    assert store.get(b"k") == b"v1"
    put(store, b"k", b"v2")
    assert store.get(b"k") == b"v2"
    store.delete(b"k")
    with pytest.raises(KeyNotFoundError):
        store.get(b"k")


def test_snapshot_isolation(store):
    put(store, b"a", b"1")
    snap = store.get_timestamp_oracle()
    put(store, b"a", b"2")
    put(store, b"b", b"9")
    assert store.get(b"a", snapshot_ts=snap) == b"1"
    assert store.get(b"a") == b"2"
    with pytest.raises(KeyNotFoundError):
        store.get(b"b", snapshot_ts=snap)
    assert list(store.iter(b"", b"", snapshot_ts=snap)) == [(b"a", b"1")]


def test_conditional_batch_conflicts(store):
    b = store.begin_batch_write()
    b.put_if_not_exist(b"k", b"v")
    b.commit()
    b2 = store.begin_batch_write()
    b2.put(b"other", b"x")
    b2.put_if_not_exist(b"k", b"v2")
    with pytest.raises(CASFailedError) as ei:
        b2.commit()
    assert ei.value.conflict.index == 1
    assert ei.value.conflict.key == b"k"
    assert ei.value.conflict.value == b"v"
    with pytest.raises(KeyNotFoundError):
        store.get(b"other")  # all-or-nothing
    # cas success + failure
    b3 = store.begin_batch_write()
    b3.cas(b"k", b"v2", b"v")
    b3.commit()
    assert store.get(b"k") == b"v2"
    with pytest.raises(CASFailedError):
        store.del_current(b"k", b"wrong")
    store.del_current(b"k", b"v2")


def test_iter_forward_reverse_limit(store):
    for k in [b"a", b"b", b"c", b"d"]:
        put(store, k, b"v" + k)
    assert [k for k, _ in store.iter(b"a", b"c")] == [b"a", b"b"]
    assert [k for k, _ in store.iter(b"", b"")] == [b"a", b"b", b"c", b"d"]
    assert [k for k, _ in store.iter(b"a", b"", limit=3)] == [b"a", b"b", b"c"]
    assert [k for k, _ in store.iter(b"c", b"a")] == [b"c", b"b", b"a"]
    assert [k for k, _ in store.iter(b"c", b"a", limit=1)] == [b"c"]


def test_native_ttl(store):
    put(store, b"/events/e1", b"v", ttl=1)
    assert store.get(b"/events/e1") == b"v"
    time.sleep(1.1)
    with pytest.raises(KeyNotFoundError):
        store.get(b"/events/e1")
    assert list(store.iter(b"/events/", b"/events0")) == []


def test_split_keys_partitions():
    s = new_storage("native", partitions=4)
    for i in range(100):
        put(s, b"key%03d" % i, b"v")
    parts = s.get_partitions(b"", b"")
    assert len(parts) == 4
    assert parts[0].left == b"" and parts[-1].right == b""
    for i in range(len(parts) - 1):
        assert parts[i].right == parts[i + 1].left
    s.close()


@pytest.mark.parametrize("engine", ["native", "tpu-native"])
def test_backend_over_native(engine):
    """MVCC semantics end-to-end over the C++ engine (and the TPU mirror
    backed by it)."""
    if engine == "native":
        store = new_storage("native")
    else:
        store = new_storage("tpu", inner="native")
    b = Backend(store, BackendConfig(event_ring_capacity=4096))
    if engine == "tpu-native":
        b.scanner._host_limit_threshold = 0
        b.scanner._merge_threshold = 8
    K = b"/registry/pods/default/nginx"
    r1 = b.create(K, b"v1")
    assert b.get(K).value == b"v1"
    r2 = b.update(K, b"v2", r1)
    assert b.get(K, revision=r1).value == b"v1"
    for i in range(10):
        b.create(b"/registry/pods/p%02d" % i, b"x%d" % i)
    res = b.list_(b"/registry/pods/", b"/registry/pods0")
    assert len(res.kvs) == 11
    n, _ = b.count(b"/registry/pods/", b"/registry/pods0")
    assert n == 11
    rev, _prev = b.delete(K)
    assert wait_for_revision(b, rev)
    res = b.list_(b"/registry/pods/", b"/registry/pods0")
    assert len(res.kvs) == 10
    done = b.compact(rev)
    assert done == rev
    # compacted rows physically gone from the C++ store
    from kubebrain_tpu import coder

    raw = store._inner if engine == "tpu-native" else store
    with pytest.raises(KeyNotFoundError):
        raw.get(coder.encode_revision_key(K))
    b.close()
    store.close()


def test_compaction_physically_frees_versions():
    """After MVCC compaction, the engine's version chains actually shrink
    (kb_prune): a long-running server must not grow memory per update."""
    store = new_storage("native")
    b = Backend(store, BackendConfig(event_ring_capacity=8192))
    K = b"/registry/churn/a"
    rev = b.create(K, b"v0")
    for i in range(50):
        rev = b.update(K, b"v%d" % i, rev)
    KD = b"/registry/churn/dead"
    rd = b.create(KD, b"x")
    rdel, _ = b.delete(KD, rd)
    assert wait_for_revision(b, rdel)
    before = store.version_count()
    b.compact(rdel)
    after = store.version_count()
    assert after < before // 2, f"prune ineffective: {before} -> {after}"
    # live state intact, deleted key fully erased at the engine level
    assert b.get(K).value == b"v49"
    from kubebrain_tpu import coder

    with pytest.raises(KeyNotFoundError):
        store.get(coder.encode_revision_key(KD))
    # and further writes still work
    rev2 = b.update(K, b"post", rev)
    assert b.get(K).value == b"post" and rev2 > rev
    b.close()
    store.close()


def test_native_scanner_differential_vs_generic(tmp_path):
    """NativeScanner (C MVCC list pass, kb_mvcc_list_page) must match the
    generic per-row scanner exactly: same random op sequence on a native
    and a memkv backend, compare lists/counts/snapshots/streams/limits."""
    import numpy as np

    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.storage import new_storage
    from kubebrain_tpu.storage.native import NativeScanner

    cfg = BackendConfig(event_ring_capacity=4096, watch_cache_capacity=4096)
    sn = new_storage("native", partitions=4)
    sm = new_storage("memkv")
    bn, bm = Backend(sn, cfg), Backend(sm, cfg)
    assert isinstance(bn.scanner, NativeScanner)
    rng = np.random.RandomState(7)
    snaps = []
    try:
        for i in range(120):
            k = b"/registry/nd/k%03d" % rng.randint(0, 40)
            delete = rng.rand() < 0.25
            for b in (bn, bm):
                try:
                    b.create(k, b"v%d" % i)
                except Exception:
                    kv = b.get(k)
                    if delete:
                        b.delete(k)
                    else:
                        b.update(k, b"u%d" % i, kv.revision)
            if i % 25 == 10:
                snaps.append(bn.current_revision())
        assert bn.current_revision() == bm.current_revision()
        for rev in snaps + [0]:
            rn = bn.list_(b"/registry/nd/", b"/registry/nd0", revision=rev)
            rm = bm.list_(b"/registry/nd/", b"/registry/nd0", revision=rev)
            assert [(kv.key, kv.value, kv.revision) for kv in rn.kvs] == \
                   [(kv.key, kv.value, kv.revision) for kv in rm.kvs]
        cn, _ = bn.count(b"/registry/nd/", b"/registry/nd0")
        cm, _ = bm.count(b"/registry/nd/", b"/registry/nd0")
        assert cn == cm
        # limit paging parity
        rn = bn.list_(b"/registry/nd/", b"/registry/nd0", limit=7)
        rm = bm.list_(b"/registry/nd/", b"/registry/nd0", limit=7)
        assert rn.more == rm.more
        assert [kv.key for kv in rn.kvs] == [kv.key for kv in rm.kvs]
        # stream parity
        s1 = [kv.key for batch in bn.scanner.range_stream(b"/", b"", bn.current_revision()) for kv in batch]
        s2 = [kv.key for batch in bm.scanner.range_stream(b"/", b"", bm.current_revision()) for kv in batch]
        assert s1 == s2
        # tiny pages exercise the cross-page pending-key carry
        bn.scanner.PAGE_ROWS = 3
        rn = bn.list_(b"/registry/nd/", b"/registry/nd0")
        rm_full = bm.list_(b"/registry/nd/", b"/registry/nd0")
        assert [(kv.key, kv.value) for kv in rn.kvs] == \
               [(kv.key, kv.value) for kv in rm_full.kvs]
    finally:
        bn.close(); bm.close(); sn.close(); sm.close()
