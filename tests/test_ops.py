"""Kernel unit tests: packing, lex compare, visibility, fan-out, compaction —
differential-tested against Python oracles on random MVCC datasets."""

import numpy as np
import pytest

import jax.numpy as jnp

from kubebrain_tpu.ops import keys as keyops
from kubebrain_tpu.ops.compact import compact_block, victim_mask
from kubebrain_tpu.ops.fanout import fanout_mask
from kubebrain_tpu.ops.scan import lex_less, visibility_mask


def test_pack_roundtrip():
    ks = [b"/registry/pods/a", b"", b"x" * 128, b"ab"]
    chunks, lens = keyops.pack_keys(ks)
    assert chunks.shape == (4, 32) and list(lens) == [16, 0, 128, 2]
    assert keyops.chunks_to_bytes(chunks, lens) == ks
    with pytest.raises(ValueError):
        keyops.pack_keys([b"y" * 129])


def test_pack_order_preserving():
    rng = np.random.RandomState(0)
    ks = sorted(
        bytes(rng.randint(1, 255, rng.randint(1, 60), dtype=np.uint8)) for _ in range(200)
    )
    chunks, _ = keyops.pack_keys(ks)
    # tuple order of packed chunks == lexicographic byte order
    as_tuples = [tuple(int(x) for x in row) for row in chunks]
    assert as_tuples == sorted(as_tuples)


def test_split_revs():
    revs = np.array([0, 1, 2**31, 2**32 + 5, 2**53], dtype=np.uint64)
    hi, lo = keyops.split_revs(revs)
    assert (keyops.join_revs(hi, lo) == revs).all()


def test_lex_less_matches_python():
    rng = np.random.RandomState(1)
    ks = [bytes(rng.randint(1, 255, rng.randint(1, 40), dtype=np.uint8)) for _ in range(100)]
    bound = ks[50]
    chunks, _ = keyops.pack_keys(ks)
    got = np.asarray(lex_less(jnp.asarray(chunks), jnp.asarray(keyops.pack_one(bound))))
    want = np.array([k < bound for k in ks])
    assert (got == want).all()


def _oracle_visible(rows, start, end, read_rev):
    """rows: sorted (key, rev, tomb). Returns set of visible (key, rev)."""
    best = {}
    for k, rev, tomb in rows:
        if k < start or (end and k >= end):
            continue
        if rev <= read_rev:
            best[k] = (rev, tomb)
    return {(k, rv) for k, (rv, tomb) in best.items() if not tomb}


def _random_dataset(seed, n_keys=60, max_revs=6):
    rng = np.random.RandomState(seed)
    keys = sorted(
        {b"/reg/" + bytes(rng.randint(97, 123, rng.randint(1, 12), dtype=np.uint8)) for _ in range(n_keys)}
    )
    rows = []
    rev = 0
    per_key = {k: [] for k in keys}
    order = [k for k in keys for _ in range(rng.randint(1, max_revs))]
    rng.shuffle(order)
    for k in order:
        rev += 1
        tomb = rng.rand() < 0.2
        per_key[k].append((rev, tomb))
    for k in keys:
        for r, t in per_key[k]:
            rows.append((k, r, t))
    rows.sort(key=lambda x: (x[0], x[1]))
    return rows, rev


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_visibility_mask_vs_oracle(seed):
    rows, max_rev = _random_dataset(seed)
    chunks, _ = keyops.pack_keys([r[0] for r in rows])
    hi, lo = keyops.split_revs(np.array([r[1] for r in rows], dtype=np.uint64))
    tomb = np.array([r[2] for r in rows])
    n = len(rows)
    for read_rev in [1, max_rev // 2, max_rev]:
        for start, end in [(b"", b""), (b"/reg/c", b"/reg/p"), (b"/reg/zz", b"")]:
            mask = np.asarray(
                visibility_mask(
                    jnp.asarray(chunks), jnp.asarray(hi), jnp.asarray(lo),
                    jnp.asarray(tomb), jnp.asarray(np.int32(n)),
                    jnp.asarray(keyops.pack_one(start)), jnp.asarray(keyops.pack_one(end)),
                    jnp.asarray(not end), *[jnp.asarray(x[0]) for x in keyops.split_revs(np.array([read_rev], dtype=np.uint64))],
                )
            )
            got = {(rows[i][0], rows[i][1]) for i in np.nonzero(mask)[0]}
            want = _oracle_visible(rows, start, end, read_rev)
            assert got == want, f"seed={seed} rev={read_rev} range=({start},{end})"


def test_visibility_padding_rows_excluded():
    rows = [(b"/a", 1, False), (b"/b", 2, False)]
    chunks, _ = keyops.pack_keys([r[0] for r in rows] + [b"", b""])
    hi, lo = keyops.split_revs(np.array([1, 2, 0, 0], dtype=np.uint64))
    tomb = np.zeros(4, dtype=bool)
    mask = np.asarray(
        visibility_mask(
            jnp.asarray(chunks), jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(tomb),
            jnp.asarray(np.int32(2)),
            jnp.asarray(keyops.pack_one(b"")), jnp.asarray(keyops.pack_one(b"")),
            jnp.asarray(True),
            *[jnp.asarray(x[0]) for x in keyops.split_revs(np.array([5], dtype=np.uint64))],
        )
    )
    assert mask.tolist() == [True, True, False, False]


def test_fanout_mask():
    events = [b"/registry/pods/default/a", b"/registry/services/x", b"/registry/pods/kube/b"]
    ek, _ = keyops.pack_keys(events)
    ehi, elo = keyops.split_revs(np.array([10, 11, 12], dtype=np.uint64))
    prefixes = [b"/registry/pods/", b"/registry/", b"/registry/pods/kube"]
    min_revs = [0, 11, 0]
    pc, pm = keyops.chunk_prefix_masks(prefixes)
    whi, wlo = keyops.split_revs(np.array(min_revs, dtype=np.uint64))
    mask = np.asarray(
        fanout_mask(jnp.asarray(ek), jnp.asarray(ehi), jnp.asarray(elo),
                    jnp.asarray(pc), jnp.asarray(pm), jnp.asarray(whi), jnp.asarray(wlo))
    )
    assert mask.tolist() == [
        [True, False, False],   # ev0 rev10: pods✓, registry(minrev11)✗, kube✗
        [False, True, False],   # ev1 rev11: services
        [True, True, True],     # ev2 rev12: all match
    ]


def test_victim_mask_and_compact_block():
    # key /a: revs 1,3 (3 live); /b: rev 2 tombstone; /events/e: revs 4,5
    rows = [
        (b"/a", 1, False),
        (b"/a", 3, False),
        (b"/b", 2, True),
        (b"/events/e", 4, False),
        (b"/events/e", 5, False),
    ]
    chunks, _ = keyops.pack_keys([r[0] for r in rows])
    hi, lo = keyops.split_revs(np.array([r[1] for r in rows], dtype=np.uint64))
    tomb = np.array([r[2] for r in rows])
    ttl = np.array([r[0].startswith(b"/events/") for r in rows])
    n = jnp.asarray(np.int32(len(rows)))

    def run(compact_rev, ttl_cutoff):
        chi, clo = keyops.split_revs(np.array([compact_rev], dtype=np.uint64))
        thi, tlo = keyops.split_revs(np.array([ttl_cutoff], dtype=np.uint64))
        return np.asarray(
            victim_mask(jnp.asarray(chunks), jnp.asarray(hi), jnp.asarray(lo),
                        jnp.asarray(tomb), jnp.asarray(ttl), n,
                        jnp.asarray(chi[0]), jnp.asarray(clo[0]),
                        jnp.asarray(thi[0]), jnp.asarray(tlo[0]))
        )

    # compact@3, no TTL: /a rev1 superseded (rev3 survives as last <=3);
    # /b tombstone dead; /events keep
    assert run(3, 0).tolist() == [True, False, True, False, False]
    # compact@5 + TTL cutoff 5: /events group fully expired on top
    assert run(5, 5).tolist() == [True, False, True, True, True]
    # compact@1: nothing superseded (rev1 is last <=1 for /a, live)
    assert run(1, 0).tolist() == [False, False, False, False, False]

    mask = jnp.asarray(run(3, 0))
    k2, h2, l2, t2, cnt = compact_block(jnp.asarray(chunks), jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(tomb), mask)
    assert int(cnt) == 3  # /a@3, /events@4, /events@5
    kept = keyops.chunks_to_bytes(np.asarray(k2)[: int(cnt)], np.array([2, 9, 9]))
    assert kept == [b"/a", b"/events/e", b"/events/e"]
