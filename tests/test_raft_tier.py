"""Quorum (raft-lite) storage tier: kbstored --peers (VERDICT r3 next #1).

The reference's TiKV is a raft-quorum store (pkg/storage/tikv/tikv.go:38-153):
writes commit on majority ack and leadership moves by election. Round 3's
tier was semi-sync with operator promotion and two documented holes — the
all-follower-detach standalone degradation (acked writes that die with the
primary's disk) and forced-promotion split-brain. Quorum mode closes both:

- every member lists the same peer set; all boot followers; pre-vote +
  term/log-match elections pick the leader (term = lineage epoch);
- client ACKs release only once floor(n/2) followers durably applied the
  record — a leader below quorum REFUSES writes outright;
- writes applied on a leader that loses quorum/steps down before majority
  ack come back ST_UNCERTAIN -> UncertainResultError (honestly unknown);
- PROMOTE is refused: operators cannot fork a quorum tier.

These tests are the verdict's done-criteria: kill -9 auto-election inside a
bounded window with zero acked loss (strict-lincheck-verified under a
nemesis), quorum refusal on a partitioned ex-leader, divergent rejoin.
"""

import math
import os
import signal
import socket
import subprocess
import tempfile
import threading
import time

import pytest

from kubebrain_tpu.lincheck import History
from kubebrain_tpu.storage import new_storage
from kubebrain_tpu.storage.errors import (
    KeyNotFoundError,
    StorageError,
    UncertainResultError,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STORED_BIN = os.path.join(REPO, "native", "kvrpc", "kbstored")

pytestmark = pytest.mark.skipif(
    not os.path.exists(STORED_BIN), reason="kbstored not built (make -C native)"
)


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def put(s, key, value):
    b = s.begin_batch_write()
    b.put(key, value)
    b.commit()


class Cluster:
    """A 3-member kbstored --peers cluster with restartable members."""

    def __init__(self, tmp, n=3, election_ms=500):
        self.tmp = tmp
        self.ports = [free_port() for _ in range(n)]
        self.peers = ",".join(f"127.0.0.1:{p}" for p in self.ports)
        self.env = dict(os.environ, KB_ELECTION_TIMEOUT_MS=str(election_ms))
        self.procs: dict[int, subprocess.Popen] = {}
        for i in range(n):
            self.start(i)

    def start(self, i, boot_timeout=30.0):
        """Spawn member i and wait for its READY line — robustly.

        The old one-shot ``assert b"READY" in readline()`` raced member
        restarts under full-suite load (the ROADMAP leader-restart flake):
        a freshly killed member's socket can linger, so the respawned
        binary loses the bind race with its own predecessor and exits (or
        logs a warning line) before READY ever appears, and a wedged boot
        blocked readline() forever. Fresh-probe discipline instead: scan
        stdout line-by-line under a deadline (log lines ahead of READY are
        fine), and if the process dies before READY, respawn with backoff
        until the bind succeeds or the deadline expires."""
        import select

        path = os.path.join(self.tmp, f"n{i}")
        os.makedirs(path, exist_ok=True)
        deadline = time.time() + boot_timeout
        backoff = 0.1
        while True:
            proc = subprocess.Popen(
                [STORED_BIN, str(self.ports[i]), path,
                 "--peers", self.peers, "--self", str(i)],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=self.env)
            ready = False
            while time.time() < deadline:
                r, _, _ = select.select([proc.stdout], [], [], 0.25)
                if r:
                    line = proc.stdout.readline()
                    if not line:
                        break  # EOF: died before READY (bind race)
                    if b"READY" in line:
                        ready = True
                        break
                    continue  # a log/warning line ahead of READY is fine
                if proc.poll() is not None:
                    break  # exited without flushing anything
            if ready:
                self.procs[i] = proc
                return
            try:
                proc.kill()
                proc.wait()
            except Exception:
                pass
            if time.time() >= deadline:
                raise AssertionError(
                    f"member {i} never printed READY within {boot_timeout}s")
            time.sleep(backoff)
            backoff = min(backoff * 2, 1.0)

    def kill(self, i):
        self.procs[i].kill()
        self.procs[i].wait()
        del self.procs[i]

    def close(self):
        for p in self.procs.values():
            try:
                p.kill()
                p.wait()
            except Exception:
                pass

    def storage(self, **kw):
        kw.setdefault("pool", 2)
        kw.setdefault("timeout", 8.0)
        return new_storage("remote", address=self.peers, **kw)

    def wait_leader(self, s, timeout=15.0, min_replicas=None):
        """(leader_idx, epoch) once exactly one member leads (and, when
        asked, has at least min_replicas attached)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            leaders = []
            for i in range(len(self.ports)):
                if i not in self.procs:
                    continue
                try:
                    is_f, ts, nrep, _, ep = s.member_info(i, timeout=1.0)
                except Exception:
                    continue
                if not is_f:
                    leaders.append((i, ep, nrep))
            if len(leaders) == 1:
                i, ep, nrep = leaders[0]
                if min_replicas is None or nrep >= min_replicas:
                    return i, ep
            time.sleep(0.1)
        raise AssertionError("no single stable leader emerged")


def test_quorum_boots_and_elects_single_leader(tmp_path):
    c = Cluster(str(tmp_path))
    s = c.storage()
    try:
        leader, epoch = c.wait_leader(s, min_replicas=2)
        assert epoch >= 1
        put(s, b"/q/a", b"1")  # write path up end to end
        assert s.get(b"/q/a") == b"1"
        # PROMOTE is an operator fork attempt: refused in quorum mode
        with pytest.raises(StorageError, match="election"):
            s.promote((leader + 1) % 3, force=True)
    finally:
        s.close()
        c.close()


def test_quorum_refuses_writes_below_majority(tmp_path):
    """Kill both followers: the leader must REFUSE writes (definite, before
    apply) — never the legacy standalone acking whose acks die with the
    leader's disk (kbstored.cc:512-514 in round 3)."""
    c = Cluster(str(tmp_path))
    s = c.storage()
    try:
        leader, _ = c.wait_leader(s, min_replicas=2)
        put(s, b"/q/pre", b"1")
        for i in range(3):
            if i != leader:
                c.kill(i)
        time.sleep(0.5)  # let the leader notice the detachments
        with pytest.raises(StorageError, match="no quorum|refused"):
            put(s, b"/q/lost", b"2")
        # reads still served (stale-tolerant by design; snapshot reads are
        # what correctness rests on)
        assert s.get(b"/q/pre") == b"1"
        # restart one follower: quorum restored, writes flow again
        for i in range(3):
            if i != leader:
                c.start(i)
                break
        deadline = time.time() + 15
        while True:
            try:
                put(s, b"/q/back", b"3")
                break
            except (StorageError, UncertainResultError):
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        assert s.get(b"/q/back") == b"3"
    finally:
        s.close()
        c.close()


def test_quorum_uncertain_not_silent_on_stalled_followers(tmp_path):
    """SIGSTOP both followers: an in-flight write (applied on the leader,
    never majority-acked) must surface as UncertainResultError within the
    quorum ack timeout — neither a success lie nor a definite-failure lie."""
    c = Cluster(str(tmp_path))
    s = c.storage()
    try:
        leader, _ = c.wait_leader(s, min_replicas=2)
        put(s, b"/q/pre", b"1")
        # stall (not kill) the followers: conns stay open, acks never come;
        # the quorum ack timeout (default 2s) must expire the held write
        for i in range(3):
            if i != leader:
                os.kill(c.procs[i].pid, signal.SIGSTOP)
        try:
            t0 = time.monotonic()
            with pytest.raises(UncertainResultError):
                put(s, b"/q/inflight", b"2")
            # bound = the 2s quorum ack timeout + transport/retry overhead,
            # with headroom for host-scheduling noise on the CI runner
            assert time.monotonic() - t0 < 20.0
        finally:
            for i in range(3):
                if i != leader and i in c.procs:
                    os.kill(c.procs[i].pid, signal.SIGCONT)
    finally:
        s.close()
        c.close()


def test_quorum_kill9_leader_auto_elects_no_acked_loss(tmp_path):
    """The verdict's done-criterion (a): kill -9 the leader under live
    write load; the tier must elect a new leader inside a bounded window
    with ZERO acked writes lost, and the whole concurrent history must be
    strictly linearizable (no truncated lincheck searches)."""
    c = Cluster(str(tmp_path))
    s = c.storage()
    history = History()
    acked: dict[bytes, int] = {}
    lock = threading.Lock()
    stop = threading.Event()
    # Ack-order revision counter: assigned under the lock AT RETURN TIME,
    # so it respects real time across keys exactly as the checker's global
    # revision pass demands (A returned before B called => rev(A) < rev(B)).
    rev_counter = [0]

    # Bounded-window discipline (the linearizability suites' rendezvous,
    # tests/test_linearizability.py::_soak): a periodic all-writer barrier
    # bounds how far preempted writer threads can stretch op windows under
    # host load — no op interval spans a rendezvous instant, so the
    # checker's per-key segments and the global pass always see short
    # windows, regardless of how the CI host schedules the threads. The
    # barrier times out (a writer wedged in a failover-window RPC must not
    # wedge the others) and degrades to the unfenced soak.
    barrier = threading.Barrier(4)

    def writer(w):
        i = 0
        last_rendezvous = 0
        while not stop.is_set():
            # fire ONCE per 25-op boundary: the StorageError retry path
            # below does not advance i, and re-parking at the barrier on
            # every failover-window retry would break it for good
            if i - last_rendezvous >= 25:
                last_rendezvous = i
                try:
                    barrier.wait(timeout=30.0)
                except threading.BrokenBarrierError:
                    pass
            key = b"/soak/w%02d-%05d" % (w, i)
            t0 = time.monotonic()
            try:
                put(s, key, b"v")
                with lock:
                    rev_counter[0] += 1
                    acked[key] = rev_counter[0]
                    history.record(w, "create", key, t0, time.monotonic(),
                                   value=b"v", ok=True, rev=rev_counter[0])
                i += 1
            except UncertainResultError:
                with lock:
                    history.record(w, "create", key, t0, math.inf,
                                   value=b"v", ok=None)
                i += 1
            except (StorageError, OSError):
                time.sleep(0.05)

    try:
        leader0, epoch0 = c.wait_leader(s, min_replicas=2)
        writers = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
        for t in writers:
            t.start()
        time.sleep(1.0)
        t_kill = time.monotonic()
        c.kill(leader0)
        # The observation (wait_leader's member_info polls, 1s RPC timeout
        # per member per round) lags the election itself under CI load —
        # the bound asserts "elects inside a bounded window", and the
        # window must absorb host-scheduling noise on the 2-vCPU runner,
        # not just the 500ms election timeout. 30s is still a hard bound;
        # the typical measured window is 1-3s.
        leader1, epoch1 = c.wait_leader(s, timeout=40.0)
        t_elect = time.monotonic()
        elect_window = t_elect - t_kill
        assert leader1 != leader0 and epoch1 > epoch0
        assert elect_window < 30.0, f"election took {elect_window:.1f}s"
        time.sleep(1.5)  # post-failover progress
        stop.set()
        barrier.abort()  # release any writer parked at the rendezvous
        for t in writers:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in writers)
        assert len(acked) > 30, f"writers made little progress: {len(acked)}"

        # Close the uncertain-op windows (the test_linearizability round-5
        # discipline; ADVICE.md): uncapped ret=inf windows overlap every
        # later op, and under CI load the checker's search explodes on them
        # — the known load-sensitive flake mode. Cap ONLY ops whose call
        # preceded the SIGKILL: a post-kill uncertain op can be re-issued to
        # the new leader by the remote tier's retry loop, so its true
        # linearization point may land after election and capping it would
        # fabricate a violation. The cap VALUE is election-complete time
        # (t_elect): a pre-kill frame can still drain from follower buffers
        # a few ms past primary death, but by the time the new term is
        # elected those frames have long applied or died with the leader.
        # Mutate only after proving every writer thread is gone (asserted
        # above) — a live writer could still be appending to the history.
        for op in list(history.ops):
            if op.ok is None and op.ret == math.inf and op.call < t_kill:
                op.ret = t_elect
        # Post-kill uncertain ops keep windows open past election (their
        # retried effect may land after t_elect — ADVICE round 5), but NOT
        # past this point: every writer thread is proven dead (asserted
        # above), so no retry loop is in flight and nothing can commit one
        # of these records after now. Capping here bounds EVERY remaining
        # window before the read-back fold — the soak has no reads between
        # the kill and the fold, so the cap cannot exclude a linearization
        # point some earlier observation depends on.
        t_cap = time.monotonic()
        for op in list(history.ops):
            if op.ok is None and op.ret == math.inf:
                op.ret = t_cap

        # zero acked loss, read back from the NEW leader
        missing = [k for k in acked if _get(s, k) is None]
        assert not missing, f"lost {len(missing)} acked writes: {missing[:5]}"

        # strict linearizability over the concurrent run: fold the final
        # state in as completed reads (unknown-outcome keys resolve either
        # way; acked keys must be present)
        t_end = time.monotonic()
        for op in list(history.ops):
            v = _get(s, op.key)
            if v is not None:
                # acked keys read back at their recorded revision; a landed
                # unknown-outcome key reveals its (uncaptured) revision as 0
                history.record(99, "get", op.key, t_end, t_end + 1e-3,
                               value=v, ok=True, rev=acked.get(op.key, 0))
            else:
                history.record(99, "get", op.key, t_end, t_end + 1e-3,
                               ok=False)
            t_end += 2e-3
        res = history.check()
        assert res["ok"], f"tier history not linearizable: {res['violation']}"
        assert not res.get("truncated") and res["truncated_keys"] == []
        print(f"[raft-soak] elect={elect_window:.2f}s acked={len(acked)} "
              f"ops={res['ops']} nodes={res['nodes_searched']}")
    finally:
        stop.set()
        s.close()
        c.close()


def _get(s, key):
    try:
        return s.get(key)
    except (KeyNotFoundError, StorageError, OSError):
        return None


@pytest.mark.slow
def test_partitioned_exleader_cannot_ack_and_rejoins(tmp_path):
    """Done-criteria (b) + divergent rejoin: freeze the leader (partition
    stand-in), let the rest elect; the thawed ex-leader must (1) hold
    divergent never-acked records only until it rejoins, (2) refuse writes
    for lack of quorum, (3) step down to follower of the new term, with the
    divergent suffix wiped by the rejoin dump."""
    c = Cluster(str(tmp_path))
    s = c.storage()
    try:
        leader0, epoch0 = c.wait_leader(s, min_replicas=2)
        put(s, b"/p/committed", b"1")
        os.kill(c.procs[leader0].pid, signal.SIGSTOP)
        # majority side elects a new term
        s2 = c.storage()
        try:
            leader1, epoch1 = c.wait_leader(s2, timeout=20.0)
            assert leader1 != leader0 and epoch1 > epoch0
            put(s2, b"/p/after", b"2")  # quorum side keeps committing
            # thaw the ex-leader: its replicas are gone; writes to it must
            # be REFUSED (no quorum), not silently acked
            os.kill(c.procs[leader0].pid, signal.SIGCONT)
            direct = new_storage(
                "remote", address=f"127.0.0.1:{c.ports[leader0]}",
                pool=1, timeout=5.0)
            try:
                with pytest.raises((StorageError, UncertainResultError)):
                    put(direct, b"/p/fork", b"X")
            finally:
                direct.close()
            # ...and within a few probe ticks it steps down and follows
            deadline = time.time() + 20
            while time.time() < deadline:
                try:
                    is_f, _, _, _, ep = s2.member_info(leader0, timeout=1.0)
                    if is_f and ep == epoch1:
                        break
                except Exception:
                    pass
                time.sleep(0.2)
            else:
                raise AssertionError("ex-leader never stepped down")
            # the quorum side's data is intact and visible everywhere
            assert s2.get(b"/p/committed") == b"1"
            assert s2.get(b"/p/after") == b"2"
            # the fork attempt never became durable state on the tier
            with pytest.raises(KeyNotFoundError):
                s2.get(b"/p/fork")
        finally:
            s2.close()
    finally:
        s.close()
        c.close()


def test_quorum_leader_restart_rejoins_as_follower(tmp_path):
    """kill -9 the leader, let a new term start, restart the old binary
    with its old data dir: it must come back as a FOLLOWER of the new term
    (persisted term + discovery), with all quorum-committed data served."""
    c = Cluster(str(tmp_path))
    s = c.storage()
    try:
        leader0, epoch0 = c.wait_leader(s, min_replicas=2)
        for i in range(30):
            put(s, b"/r/k%02d" % i, b"v%02d" % i)
        c.kill(leader0)
        leader1, epoch1 = c.wait_leader(s, timeout=20.0)
        assert epoch1 > epoch0
        c.start(leader0)
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                is_f, _, _, _, ep = s.member_info(leader0, timeout=1.0)
                if is_f and ep >= epoch1:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        else:
            raise AssertionError("restarted ex-leader never rejoined")
        for i in range(30):
            assert s.get(b"/r/k%02d" % i) == b"v%02d" % i
        put(s, b"/r/post", b"1")
        assert s.get(b"/r/post") == b"1"
    finally:
        s.close()
        c.close()
