"""Read scale-out (kubebrain_tpu/replica, docs/replication.md):

- fence-read correctness: a follower's linearizable read is byte-identical
  to the leader's under concurrent (group-commit-batched) writers, and a
  fence across the leader's revision GAPS (failed ops) completes via the
  ordered watch progress marks;
- bounded-staleness enforcement: a follower past its staleness bound
  REFUSES serializable reads (etcdserver-prefixed UNAVAILABLE — the safe
  class clients fail over on) and degrades to explicit-revision-only
  serving; a stalled watermark turns linearizable reads into fence-timeout
  refusals, never stale answers;
- follower watch resume: a replication-stream reset loses no event and
  duplicates none for the follower's OWN watchers;
- bootstrap floor: history below the follower's bootstrap revision
  refuses as compacted (the honest etcd answer);
- follower mirror identity (--storage=tpu, jnp + pallas-interpret): the
  replicated delta blocks seal into the same serving state the leader
  has, byte-identical at a pinned revision through the real gRPC front;
- a small-N two-replica end-to-end smoke through the workload harness
  (spawned processes, real gRPC, schema'd replica report section).
"""

import threading
import time

import grpc
import pytest

from kubebrain_tpu.cli import build_endpoint, build_parser
from kubebrain_tpu.client import EtcdCompatClient, WatchMux

from test_etcd_server import free_port


class Node:
    """One in-process server (leader or follower) built through the real
    cli wiring, serving on real ports."""

    def __init__(self, argv):
        args = build_parser().parse_args(argv)
        self.endpoint, self.backend, self.store = build_endpoint(args)
        self.endpoint.run()
        self.client_port = args.client_port
        self.info_port = args.info_port
        self.target = f"127.0.0.1:{args.client_port}"
        self.role = getattr(self.endpoint.server, "replica", None)

    def close(self):
        self.endpoint.close()
        self.backend.close()
        self.store.close()


def spawn_pair(storage="memkv", leader_extra=(), follower_extra=(),
               preload=0):
    lc, lp, li = free_port(), free_port(), free_port()
    leader = Node(["--single-node", "--storage", storage,
                   "--host", "127.0.0.1",
                   "--client-port", str(lc), "--peer-port", str(lp),
                   "--info-port", str(li), "--compact-interval", "86400",
                   *leader_extra])
    lcli = EtcdCompatClient(leader.target)
    for i in range(preload):
        ok, _ = lcli.create(b"/registry/pods/ns0/pre%03d" % i, b"v0")
        assert ok
    fc, fp, fi = free_port(), free_port(), free_port()
    follower = Node(["--role", "follower",
                     "--leader-address", leader.target,
                     "--leader-info", f"127.0.0.1:{li}",
                     "--storage", storage, "--host", "127.0.0.1",
                     "--client-port", str(fc), "--peer-port", str(fp),
                     "--info-port", str(fi), "--compact-interval", "86400",
                     *follower_extra])
    fcli = EtcdCompatClient(follower.target)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            fcli.count(b"/probe", b"/probe0")
            break
        except grpc.RpcError:
            time.sleep(0.1)
    else:
        raise RuntimeError("follower never served")
    return leader, lcli, follower, fcli


PODS = b"/registry/pods/"
PODS_END = b"/registry/pods0"


def rows(kvs):
    return [(k.key, k.value, k.mod_revision) for k in kvs]


def _divergence_diagnostics(leader, follower, kvs_f, kvs_l, fence, got):
    """Rich dump for a follower-vs-leader pinned-revision mismatch: the
    per-key revision records on BOTH stores tell exactly which revisions
    the follower is missing relative to its claimed watermark."""
    sf = {(k.key, k.mod_revision) for k in kvs_f}
    sl = {(k.key, k.mod_revision) for k in kvs_l}
    lines = [f"DIVERGED at fence={fence} follower_got={got} "
             f"wm={follower.backend.tso.committed()} "
             f"leader_committed={leader.backend.tso.committed()}"]
    lines.append(f"stream={follower.role.status()['stream']}")
    for label, only in (("follower-only", sf - sl), ("leader-only", sl - sf)):
        for key, rev in sorted(only)[:6]:
            lrec = leader.backend._read_rev_record(key)
            frec = follower.backend._read_rev_record(key)
            lines.append(f"{label} {key!r}@{rev}: leader_rec={lrec} "
                         f"follower_rec={frec}")
    return "\n".join(lines)


# ----------------------------------------------------------- fence reads
def test_fence_read_correctness_under_concurrent_writers():
    leader, lcli, follower, fcli = spawn_pair()
    try:
        stop = threading.Event()
        errs = []

        def writer(wid):
            c = EtcdCompatClient(leader.target)
            try:
                rev = 0
                i = 0
                while not stop.is_set():
                    key = b"/registry/pods/nsw/%d-%d" % (wid, i)
                    ok, rev = c.create(key, b"x" * 64)
                    if ok and i % 3 == 0:
                        c.update(key, b"y" * 64, rev)
                    if ok and i % 5 == 0:
                        c.delete(key, 0)
                    i += 1
            except Exception as e:  # pragma: no cover
                errs.append(e)
            finally:
                c.close()

        # several concurrent writers so the scheduler actually forms
        # commit groups on the leader (docs/writes.md)
        threads = [threading.Thread(target=writer, args=(w,), daemon=True)
                   for w in range(4)]
        for t in threads:
            t.start()
        try:
            probes = 0
            deadline = time.monotonic() + 60
            while probes < 10 and time.monotonic() < deadline:
                # linearizable fence probe: leader revision first, then a
                # rev-0 non-serializable read on the follower must come
                # back at or above it. A fence-timeout REFUSAL under box
                # load is legal (the contract is refusals, never stale
                # answers) — retry it; a below-fence answer never is.
                fence = lcli.current_revision()
                if fence == 0:
                    # nothing committed yet (writers still starting):
                    # list(revision=0) would be a HEAD read, not a pinned
                    # one, and head reads at two different instants
                    # legitimately differ — the degenerate case behind a
                    # long-lived "divergence" flake in this test
                    continue
                try:
                    got = fcli.current_revision()
                except grpc.RpcError as e:
                    assert "replica refused" in (e.details() or "")
                    continue
                assert got >= fence, (got, fence)
                # explicit pinned revision: byte-identical to the leader
                kvs_f, _ = fcli.list(PODS, PODS_END, revision=fence)
                kvs_l, _ = lcli.list(PODS, PODS_END, revision=fence)
                if rows(kvs_f) != rows(kvs_l):
                    diag = _divergence_diagnostics(
                        leader, follower, kvs_f, kvs_l, fence, got)
                    raise AssertionError(diag)
                probes += 1
            assert probes >= 3, "too few successful fence probes"
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errs
    finally:
        fcli.close()
        lcli.close()
        follower.close()
        leader.close()


def test_fence_crosses_revision_gaps_via_progress_marks():
    # a FAILED leader write consumes a revision but streams no event: the
    # follower can only reach the new committed floor through the ordered
    # progress marks — a fenced read right after must still complete
    leader, lcli, follower, fcli = spawn_pair()
    try:
        ok, rev1 = lcli.create(b"/registry/pods/ns0/a", b"v")
        assert ok
        ok, _rev2 = lcli.update(b"/registry/pods/ns0/a", b"w", rev1)
        assert ok
        # update against the STALE revision: the CAS conflict consumes a
        # dealt revision but streams no event = a revision gap
        ok3, _ = lcli.update(b"/registry/pods/ns0/a", b"x", rev1)
        assert not ok3
        fence = lcli.current_revision()
        t0 = time.monotonic()
        got = fcli.current_revision()  # fenced on the follower
        assert got >= fence
        assert time.monotonic() - t0 < 3.0  # progress mark, not timeout
    finally:
        fcli.close()
        lcli.close()
        follower.close()
        leader.close()


def test_fence_leader_revision_never_predates_the_call():
    """The fence's leader-revision sample must come from a fetch that
    STARTED after the read arrived: joining an already-in-flight /status
    fetch could return a revision sampled before a write this read must
    observe — a real-time linearizability hole (the ticketed-singleflight
    regression)."""
    from kubebrain_tpu.replica.role import FollowerConfig, FollowerRole

    cfg = FollowerConfig(leader_address="unused:1", leader_info="unused:2",
                         fence_timeout_s=5.0)
    role = FollowerRole(None, cfg)
    rev_box = [10]
    first_started = threading.Event()
    gate = threading.Event()

    def fetch():
        v = rev_box[0]  # the leader's revision AT FETCH START
        first_started.set()
        gate.wait(5)
        return v

    role._syncer._fetch = fetch
    out = {}
    a = threading.Thread(
        target=lambda: out.__setitem__("a", role.leader_revision()))
    a.start()
    assert first_started.wait(5)
    rev_box[0] = 20  # the leader advanced AFTER fetch #1 began
    b = threading.Thread(
        target=lambda: out.__setitem__("b", role.leader_revision()))
    b.start()
    time.sleep(0.1)  # b must be parked on generation 2, not flight 1
    gate.set()
    a.join(5)
    b.join(5)
    assert out["a"] == 10      # a arrived before the advance: 10 is legal
    assert out["b"] == 20, out  # b arrived after: the stale flight is not


def test_fence_survives_a_waiter_timeout():
    """A waiter timing out while a fetch is in flight must not wedge the
    generation singleflight: later fences still get fresh fetches (the
    pre-committed-producer regression)."""
    from kubebrain_tpu.replica.role import (
        FollowerConfig, FollowerRole, LeaderUnreachableError)

    cfg = FollowerConfig(leader_address="unused:1", leader_info="unused:2",
                         fence_timeout_s=5.0)
    role = FollowerRole(None, cfg)
    gate = threading.Event()
    started = threading.Event()

    def fetch():
        started.set()
        gate.wait(5)
        return 7

    role._syncer._fetch = fetch
    a = threading.Thread(target=role.leader_revision, daemon=True)
    a.start()
    assert started.wait(5)
    # b times out while a's fetch is in flight (needs generation 2,
    # which nobody ever produces before its deadline)
    with pytest.raises(LeaderUnreachableError):
        role.leader_revision(timeout=0.05)
    gate.set()
    a.join(5)
    # the path must still work: c runs generation 2 itself
    assert role.leader_revision(timeout=5.0) == 7


def test_resync_converges_state_and_emits_deletes():
    """Rung 3 of the degradation ladder: a follower whose resume point
    fell out of the leader's cache re-lists and diffs — changed keys
    re-applied, vanished keys tombstoned (watch-visible), state
    byte-identical after."""
    leader, lcli, follower, fcli = spawn_pair()
    try:
        keys = {}
        for i in range(8):
            k = b"/registry/pods/ns0/rs%d" % i
            ok, rev = lcli.create(k, b"v%d" % i)
            assert ok
            keys[k] = rev
        deadline = time.monotonic() + 10
        while follower.role.applied_revision() < max(keys.values()):
            assert time.monotonic() < deadline
            time.sleep(0.02)
        # partition: stop the stream, then mutate the leader underneath
        follower.role._stream.close()
        time.sleep(0.3)
        lcli.delete(b"/registry/pods/ns0/rs0", 0)
        lcli.update(b"/registry/pods/ns0/rs1", b"changed", keys[
            b"/registry/pods/ns0/rs1"])
        lcli.create(b"/registry/pods/ns0/rs-new", b"fresh")
        # follower-local watcher must see the diff as events
        mux = WatchMux(fcli, streams=1)
        w = mux.add(PODS, PODS_END,
                    start_revision=follower.role.applied_revision() + 1)
        # drive the resync directly (the reconnect loop would take it on
        # a compacted cancel; forcing leader-cache expiry is impractical
        # in-test)
        probe = EtcdCompatClient(leader.target)
        try:
            follower.role._stream._resync(probe)
        finally:
            probe.close()
        kvs_f, _ = fcli.list(PODS, PODS_END, serializable=True)
        kvs_l, _ = lcli.list(PODS, PODS_END)
        assert rows(kvs_f) == rows(kvs_l)
        deadline = time.monotonic() + 10
        while w.events < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert w.events >= 3  # delete + update + create all fanned out
        mux.close()
    finally:
        fcli.close()
        lcli.close()
        follower.close()
        leader.close()


# ----------------------------------------------- staleness + degradation
def test_bounded_staleness_refusal_and_degradation_ladder():
    leader, lcli, follower, fcli = spawn_pair(
        follower_extra=["--max-staleness-ms", "300",
                        "--fence-timeout-ms", "700"])
    try:
        ok, rev = lcli.create(b"/registry/pods/ns0/k", b"v")
        assert ok
        # serializable reads promise bounded staleness, not read-your-
        # leader-writes: wait for the watermark to cover the create first
        deadline = time.monotonic() + 15
        while follower.role.applied_revision() < rev:
            assert time.monotonic() < deadline, "replication never caught up"
            time.sleep(0.05)
        # healthy: serializable reads serve locally. The 300ms bound can
        # trip transiently when the 0.2s progress ticker runs late under
        # full-suite load on a small box — retry through those; with the
        # stream LIVE a read must succeed within the deadline
        deadline = time.monotonic() + 10
        while True:
            try:
                kvs, srev = fcli.list(PODS, PODS_END, serializable=True)
                break
            except grpc.RpcError as e:
                assert "stale" in (e.details() or "")
                assert time.monotonic() < deadline, "never un-stale"
                time.sleep(0.1)
        assert len(kvs) == 1 and srev >= rev
        # stall replication: the stream stops advancing the watermark,
        # so within the deadline every serializable read must REFUSE
        follower.role._stream.close()
        wm = follower.role.applied_revision()
        time.sleep(0.5)  # past the 300ms bound
        with pytest.raises(grpc.RpcError) as ei:
            deadline = time.monotonic() + 10
            while True:
                fcli.list(PODS, PODS_END, serializable=True)
                assert time.monotonic() < deadline, "never refused"
                time.sleep(0.1)
        assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
        assert "etcdserver: replica refused (stale)" in ei.value.details()
        # degradation ladder: explicit-revision reads <= the watermark
        # STILL serve, byte-identical
        kvs_f, _ = fcli.list(PODS, PODS_END, revision=wm)
        kvs_l, _ = lcli.list(PODS, PODS_END, revision=wm)
        assert rows(kvs_f) == rows(kvs_l)
        # a linearizable read with the watermark stalled BELOW the leader
        # head must refuse (fence timeout), never answer stale
        ok, _ = lcli.create(b"/registry/pods/ns0/k2", b"v2")
        assert ok
        with pytest.raises(grpc.RpcError) as ei:
            fcli.list(PODS, PODS_END)
        assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
        assert "replica refused" in ei.value.details()
        assert follower.role.refused  # counted
    finally:
        fcli.close()
        lcli.close()
        follower.close()
        leader.close()


def test_reads_below_bootstrap_floor_refuse_as_compacted():
    # history below the follower's bootstrap revision is honestly
    # unservable: the follower refuses it as compacted so clients re-list
    leader, lcli, follower, fcli = spawn_pair(preload=10)
    try:
        assert follower.backend.compact_revision() >= 10
        with pytest.raises(grpc.RpcError) as ei:
            fcli.list(PODS, PODS_END, revision=5)
        assert ei.value.code() == grpc.StatusCode.OUT_OF_RANGE
        assert "compacted" in ei.value.details()
        # the same revision still serves on the leader
        kvs, _ = lcli.list(PODS, PODS_END, revision=5)
        assert len(kvs) == 5
    finally:
        fcli.close()
        lcli.close()
        follower.close()
        leader.close()


# ------------------------------------------------------ watch + resume
def test_follower_watch_survives_replication_reset():
    leader, lcli, follower, fcli = spawn_pair()
    try:
        ok, rev = lcli.create(b"/registry/pods/ns0/w0", b"v")
        assert ok
        # wait for the follower to apply, then watch IT from rev+1
        deadline = time.monotonic() + 10
        while follower.role.applied_revision() < rev:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        mux = WatchMux(fcli, streams=1, record_revisions=True)
        w = mux.add(PODS, PODS_END, start_revision=rev + 1)
        seen_pre = lcli.create(b"/registry/pods/ns0/w1", b"v1")[1]
        # reset the replication stream; the teardown lands at the next
        # 0.2s ticker tick, so the writes below straddle it
        stream = follower.role._stream
        stream.reset()
        revs = [seen_pre]
        for i in range(5):
            okw, r = lcli.create(b"/registry/pods/ns0/r%d" % i, b"x")
            assert okw
            revs.append(r)
            time.sleep(0.06)
        deadline = time.monotonic() + 15
        while (w.events < len(revs) or stream.resets < 1) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert stream.resets >= 1  # the reset really happened
        # exactly once, in revision order — no loss, no duplicates across
        # the replication reset
        assert w.revisions == sorted(revs), (w.revisions, revs)
        mux.close()
    finally:
        fcli.close()
        lcli.close()
        follower.close()
        leader.close()


# ------------------------------------------------- TPU mirror identity
@pytest.mark.parametrize("pallas", [False, True],
                         ids=["jnp", "pallas-interpret"])
def test_follower_mirror_identity_pinned_revision_tpu(pallas):
    extra = ["--merge-threshold", "32"]
    if pallas:
        extra = extra + ["--use-pallas"]
    leader, lcli, follower, fcli = spawn_pair(
        storage="tpu", leader_extra=extra, follower_extra=extra)
    try:
        keys = []
        for i in range(60):
            key = b"/registry/pods/ns%d/p%03d" % (i % 3, i)
            ok, rev = lcli.create(key, b"v" * (32 + i % 64))
            assert ok
            keys.append((key, rev))
        for key, rev in keys[::4]:
            lcli.update(key, b"u" * 48, rev)
        for key, _ in keys[::9]:
            lcli.delete(key, 0)
        pinned = lcli.current_revision()
        # fenced explicit-revision read: the follower's replicated delta
        # blocks (sealed into its OWN mirror via the same _DeltaIndex
        # machinery, past the 32-row merge threshold) must serve the
        # pinned snapshot byte-identically through the real gRPC front
        kvs_f, _ = fcli.list(PODS, PODS_END, revision=pinned)
        kvs_l, _ = lcli.list(PODS, PODS_END, revision=pinned)
        assert rows(kvs_f) == rows(kvs_l)
        assert len(kvs_f) > 40
        # keep writing so another merge cycle lands, then re-compare at a
        # fresh pinned revision AND at the old one (history intact)
        for i in range(40):
            lcli.create(b"/registry/pods/ns9/q%03d" % i, b"z" * 40)
        pinned2 = lcli.current_revision()
        kvs_f2, _ = fcli.list(PODS, PODS_END, revision=pinned2)
        kvs_l2, _ = lcli.list(PODS, PODS_END, revision=pinned2)
        assert rows(kvs_f2) == rows(kvs_l2)
        kvs_f3, _ = fcli.list(PODS, PODS_END, revision=pinned)
        assert rows(kvs_f3) == rows(kvs_f)
    finally:
        fcli.close()
        lcli.close()
        follower.close()
        leader.close()


# --------------------------------------------------- forwarded surfaces
def test_forwarding_and_counters():
    leader, lcli, follower, fcli = spawn_pair()
    try:
        ok, rev = fcli.create(b"/registry/pods/ns0/fwd", b"via-follower")
        assert ok
        got = lcli.get(b"/registry/pods/ns0/fwd")
        assert got is not None and got.value == b"via-follower"
        lease_id, granted = fcli.lease_grant(10)
        assert granted >= 10
        ttl, granted2, _keys = fcli.lease_time_to_live(lease_id)
        assert 0 <= ttl <= granted2
        fcli.lease_revoke(lease_id)
        fwd = follower.role.forwarded
        assert fwd["txn"] >= 1 and fwd["lease_grant"] == 1
        assert fwd["lease_ttl"] == 1 and fwd["lease_revoke"] == 1
        base = follower.role.served["range"]
        fcli.list(PODS, PODS_END, serializable=True)
        assert follower.role.served["range"] > base
    finally:
        fcli.close()
        lcli.close()
        follower.close()
        leader.close()


# --------------------------------------------- end-to-end replica smoke
def test_two_replica_end_to_end_smoke():
    """Spawned leader + 2 followers through the workload harness: real
    gRPC front, follower-routed list+watch, fence probes, the schema'd
    replica report section, and every reconcile check green."""
    from kubebrain_tpu.workload.runner import run_workload
    from kubebrain_tpu.workload.spec import WorkloadSpec

    spec = WorkloadSpec.for_smoke(8, replicas=2)
    report = run_workload(spec, write_report=False)
    assert report["slo"]["pass"], report["slo"]["violations"]
    rep = report["replica"]
    assert rep["replicas"] == 2 and len(rep["per_replica"]) == 2
    for pr in rep["per_replica"]:
        assert pr["revision_bound_ok"]
        assert pr["applied_revision"] > 0
        assert pr["served"].get("range", 0) > 0
        assert pr["max_client_revision"] <= pr["applied_revision"]
    assert rep["fence_probes"]["violations"] == 0
    assert rep["reconcile"]["ok"]
    assert report["replay"]["rows_per_sec"] > 0
    # follower-landed writes forwarded (writes round-robin over all
    # endpoints, so with 3 endpoints some MUST land on followers)
    assert sum(pr["forwarded"].get("txn", 0)
               for pr in rep["per_replica"]) > 0
