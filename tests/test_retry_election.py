"""Uncertain-write repair + leader election tests.

Reference: TestUncertainRewrite backend_test.go:1268-1386 (inject uncertain
events, assert repair converges and emits the right event sequence);
testBackendResourceLock :1044 (two backends racing over one KV lock).
"""

import time

import pytest

from kubebrain_tpu import coder
from kubebrain_tpu.backend import Backend, BackendConfig, Verb, WatchEvent, wait_for_revision
from kubebrain_tpu.backend.election import LeaderElection, ResourceLock
from kubebrain_tpu.storage import new_storage
from kubebrain_tpu.storage.errors import UncertainResultError


@pytest.fixture
def backend():
    store = new_storage("memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=1024, watch_cache_capacity=1024))
    yield b
    b.close()
    store.close()


class FlakyCommit:
    """Engine decorator whose batch commit succeeds but REPORTS uncertainty —
    the classic distributed commit-timeout (fault injection by decoration,
    reference compact_test.go:83-132)."""

    def __init__(self, store, fail_times=1):
        self._store = store
        self.remaining = fail_times

    def __getattr__(self, name):
        return getattr(self._store, name)

    def begin_batch_write(self):
        real = self._store.begin_batch_write()
        outer = self

        class B:
            def __getattr__(self, name):
                return getattr(real, name)

            def commit(self):
                real.commit()
                if outer.remaining > 0:
                    outer.remaining -= 1
                    raise UncertainResultError("injected commit timeout")

        return B()

    def mvcc_delete(self, *args, **kwargs):
        # the one-call delete fast path commits inside the engine — inject
        # the same post-commit uncertainty there (memkv deletes take
        # _delete_fast now that it implements mvcc_delete)
        out = self._store.mvcc_delete(*args, **kwargs)
        if out[0] == "ok" and self.remaining > 0:
            self.remaining -= 1
            raise UncertainResultError("injected commit timeout")
        return out


def test_uncertain_create_repair():
    store = new_storage("memkv")
    flaky = FlakyCommit(store, fail_times=1)
    b = Backend(flaky, BackendConfig(event_ring_capacity=1024))
    b.retry._probe_after = 0.0  # probe immediately in tests
    wid, q = b.watcher_hub.add_watcher(b"", b"", 0)
    with pytest.raises(UncertainResultError):
        b.create(b"/k", b"v")
    assert wait_for_revision(b, 1)
    assert len(b.retry) == 1
    assert b.retry.min_revision() == 1
    resolved = b.retry.process_ready()
    assert resolved == 1
    # repair rewrote the value at revision 2 and emitted a proper event
    assert wait_for_revision(b, 2)
    kv = b.get(b"/k")
    assert kv.value == b"v" and kv.revision == 2
    batch = q.get(timeout=5)
    assert [(e.revision, e.verb, e.key) for e in batch] == [(2, Verb.CREATE, b"/k")]
    assert len(b.retry) == 0 and b.retry.min_revision() == 0
    b.close()
    store.close()


def test_uncertain_never_landed_dropped(backend):
    """If the revision record doesn't match the uncertain revision, the op
    failed (or was superseded): the retry must drop it silently."""
    r1 = backend.create(b"/k", b"v1")
    backend.retry._probe_after = 0.0
    ghost = WatchEvent(revision=99, verb=Verb.PUT, key=b"/k", value=b"ghost", valid=False)
    backend.retry.append(ghost)
    assert backend.retry.process_ready() == 1
    assert backend.get(b"/k").value == b"v1"
    assert backend.get(b"/k").revision == r1


def test_uncertain_bounds_compaction(backend):
    r1 = backend.create(b"/k", b"v1")
    r2 = backend.update(b"/k", b"v2", r1)
    assert wait_for_revision(backend, r2)
    ghost = WatchEvent(revision=r1, verb=Verb.CREATE, key=b"/zzz", value=b"g", valid=False)
    backend.retry.append(ghost)
    # compact clamps to min-uncertain − 1 == r1 − 1 == 0 → no-op
    assert backend.compact(r2) == 0


def test_uncertain_delete_repair():
    store = new_storage("memkv")
    flaky = FlakyCommit(store, fail_times=0)
    b = Backend(flaky, BackendConfig(event_ring_capacity=1024))
    b.retry._probe_after = 0.0
    r1 = b.create(b"/k", b"v1")
    flaky.remaining = 1  # next commit (the delete) reports uncertain
    with pytest.raises(UncertainResultError):
        b.delete(b"/k")
    assert wait_for_revision(b, 2)
    assert b.retry.process_ready() == 1
    assert wait_for_revision(b, 3)
    record = b._read_rev_record(b"/k")
    assert record is not None and record[1] is True  # still deleted
    assert record[0] == 3  # at the repaired revision
    raw = store.get(coder.encode_object_key(b"/k", 3))
    from kubebrain_tpu.backend import TOMBSTONE

    assert raw == TOMBSTONE
    b.close()
    store.close()


# ---------------------------------------------------------------- election
def test_resource_lock_acquire_steal():
    store = new_storage("memkv")
    lock_a = ResourceLock(store, "node-a")
    lock_b = ResourceLock(store, "node-b")
    ea = LeaderElection(lock_a, lease_seconds=0.3, renew_interval=0.05, retry_interval=0.02)
    eb = LeaderElection(lock_b, lease_seconds=0.3, renew_interval=0.05, retry_interval=0.02)
    assert ea.try_acquire_once()
    assert not eb.try_acquire_once()
    assert ea.leader_identity() == "node-a"
    # lease expires without renewal → b steals
    time.sleep(0.35)
    assert eb.try_acquire_once()
    assert eb.leader_identity() == "node-b"
    store.close()


def test_election_campaign_callbacks():
    store = new_storage("memkv")
    store_rev_seen = []
    ea = LeaderElection(
        ResourceLock(store, "node-a"),
        on_started_leading=lambda rev: store_rev_seen.append(rev),
        lease_seconds=0.5,
        renew_interval=0.05,
        retry_interval=0.02,
    )
    ea.campaign()
    assert ea.wait_for_leadership(2.0)
    assert store_rev_seen and store_rev_seen[0] >= 0
    ea.close()
    store.close()


def test_lock_tso_seeds_revision():
    """The lock record carries the engine clock so a new leader resumes
    revisions monotonically (election.go Describe → leader.go:96-107)."""
    store = new_storage("memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=1024))
    b.create(b"/k", b"v")
    assert wait_for_revision(b, 1)
    lock = ResourceLock(store, "node-a")
    rec = lock.create()
    assert rec.tso >= 1
    b.close()
    store.close()

def test_renew_error_drops_leadership():
    """ADVICE r1 (high): a non-CAS storage error during renewal must make the
    campaign report not-leader and fire on_stopped_leading — NOT kill the
    thread with _is_leader still set (split-brain)."""
    store = new_storage("memkv")
    stopped = []
    ea = LeaderElection(
        ResourceLock(store, "node-a"),
        on_stopped_leading=lambda: stopped.append(True),
        lease_seconds=0.5,
        renew_interval=0.03,
        retry_interval=0.02,
    )
    ea.campaign()
    assert ea.wait_for_leadership(2.0)
    # sabotage the lock: every storage op now raises an unexpected error
    real_get = store.get
    store.get = lambda *a, **k: (_ for _ in ()).throw(RuntimeError("engine down"))
    deadline = time.monotonic() + 3.0
    while ea.is_leader() and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not ea.is_leader(), "leadership must drop when renewal cannot be proven"
    assert stopped, "on_stopped_leading must fire"
    store.get = real_get
    ea.close()
    store.close()


def test_retry_keeps_event_on_resolve_failure():
    """ADVICE r1 (low): a failing _resolve must not drop the uncertain event —
    it stays queued (and keeps fencing compaction) until resolution succeeds."""
    from kubebrain_tpu.backend.retry import AsyncFifoRetry

    calls = []

    def read_rev_record(key):
        calls.append(key)
        if len(calls) == 1:
            raise RuntimeError("engine hiccup")
        return (7, False)

    repaired = []
    r = AsyncFifoRetry(read_rev_record, lambda ev, rec: repaired.append(ev), probe_after=0.0)
    ev = WatchEvent(revision=7, verb=Verb.PUT, key=b"/k", value=b"v", valid=False)
    r.append(ev)
    assert r.process_ready() == 0  # first attempt fails; event retained
    assert len(r) == 1, "event must survive a failed resolve"
    assert r.min_revision() == 7, "compaction fence must hold during repair"
    assert r.process_ready() == 1
    assert repaired and repaired[0].revision == 7
    assert len(r) == 0


def test_retry_poisoned_head_dropped_after_cap():
    """A head whose resolution fails persistently must not wedge the FIFO or
    pin the compaction watermark forever: it is dropped after max_attempts."""
    from kubebrain_tpu.backend.retry import AsyncFifoRetry

    def always_fail(key):
        raise RuntimeError("persistent engine fault")

    r = AsyncFifoRetry(always_fail, lambda ev, rec: None, probe_after=0.0, max_attempts=3)
    r.append(WatchEvent(revision=5, verb=Verb.PUT, key=b"/bad", value=b"v", valid=False))
    r.append(WatchEvent(revision=6, verb=Verb.PUT, key=b"/bad2", value=b"v", valid=False))
    assert r.process_ready() == 0 and len(r) == 2  # attempt 1
    assert r.process_ready() == 0 and len(r) == 2  # attempt 2
    r.process_ready()  # attempt 3: head dropped, second entry then also fails
    assert r.min_revision() != 5, "poisoned head must stop fencing compaction"
    for _ in range(3):
        r.process_ready()
    assert len(r) == 0
