"""Uncertain-write repair + leader election tests.

Reference: TestUncertainRewrite backend_test.go:1268-1386 (inject uncertain
events, assert repair converges and emits the right event sequence);
testBackendResourceLock :1044 (two backends racing over one KV lock).
"""

import time

import pytest

from kubebrain_tpu import coder
from kubebrain_tpu.backend import Backend, BackendConfig, Verb, WatchEvent, wait_for_revision
from kubebrain_tpu.backend.election import LeaderElection, ResourceLock
from kubebrain_tpu.storage import new_storage
from kubebrain_tpu.storage.errors import UncertainResultError


@pytest.fixture
def backend():
    store = new_storage("memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=1024, watch_cache_capacity=1024))
    yield b
    b.close()
    store.close()


class FlakyCommit:
    """Engine decorator whose batch commit succeeds but REPORTS uncertainty —
    the classic distributed commit-timeout (fault injection by decoration,
    reference compact_test.go:83-132)."""

    def __init__(self, store, fail_times=1):
        self._store = store
        self.remaining = fail_times

    def __getattr__(self, name):
        return getattr(self._store, name)

    def begin_batch_write(self):
        real = self._store.begin_batch_write()
        outer = self

        class B:
            def __getattr__(self, name):
                return getattr(real, name)

            def commit(self):
                real.commit()
                if outer.remaining > 0:
                    outer.remaining -= 1
                    raise UncertainResultError("injected commit timeout")

        return B()


def test_uncertain_create_repair():
    store = new_storage("memkv")
    flaky = FlakyCommit(store, fail_times=1)
    b = Backend(flaky, BackendConfig(event_ring_capacity=1024))
    b.retry._probe_after = 0.0  # probe immediately in tests
    wid, q = b.watcher_hub.add_watcher(b"", b"", 0)
    with pytest.raises(UncertainResultError):
        b.create(b"/k", b"v")
    assert wait_for_revision(b, 1)
    assert len(b.retry) == 1
    assert b.retry.min_revision() == 1
    resolved = b.retry.process_ready()
    assert resolved == 1
    # repair rewrote the value at revision 2 and emitted a proper event
    assert wait_for_revision(b, 2)
    kv = b.get(b"/k")
    assert kv.value == b"v" and kv.revision == 2
    batch = q.get(timeout=5)
    assert [(e.revision, e.verb, e.key) for e in batch] == [(2, Verb.CREATE, b"/k")]
    assert len(b.retry) == 0 and b.retry.min_revision() == 0
    b.close()
    store.close()


def test_uncertain_never_landed_dropped(backend):
    """If the revision record doesn't match the uncertain revision, the op
    failed (or was superseded): the retry must drop it silently."""
    r1 = backend.create(b"/k", b"v1")
    backend.retry._probe_after = 0.0
    ghost = WatchEvent(revision=99, verb=Verb.PUT, key=b"/k", value=b"ghost", valid=False)
    backend.retry.append(ghost)
    assert backend.retry.process_ready() == 1
    assert backend.get(b"/k").value == b"v1"
    assert backend.get(b"/k").revision == r1


def test_uncertain_bounds_compaction(backend):
    r1 = backend.create(b"/k", b"v1")
    r2 = backend.update(b"/k", b"v2", r1)
    assert wait_for_revision(backend, r2)
    ghost = WatchEvent(revision=r1, verb=Verb.CREATE, key=b"/zzz", value=b"g", valid=False)
    backend.retry.append(ghost)
    # compact clamps to min-uncertain − 1 == r1 − 1 == 0 → no-op
    assert backend.compact(r2) == 0


def test_uncertain_delete_repair():
    store = new_storage("memkv")
    flaky = FlakyCommit(store, fail_times=0)
    b = Backend(flaky, BackendConfig(event_ring_capacity=1024))
    b.retry._probe_after = 0.0
    r1 = b.create(b"/k", b"v1")
    flaky.remaining = 1  # next commit (the delete) reports uncertain
    with pytest.raises(UncertainResultError):
        b.delete(b"/k")
    assert wait_for_revision(b, 2)
    assert b.retry.process_ready() == 1
    assert wait_for_revision(b, 3)
    record = b._read_rev_record(b"/k")
    assert record is not None and record[1] is True  # still deleted
    assert record[0] == 3  # at the repaired revision
    raw = store.get(coder.encode_object_key(b"/k", 3))
    from kubebrain_tpu.backend import TOMBSTONE

    assert raw == TOMBSTONE
    b.close()
    store.close()


# ---------------------------------------------------------------- election
def test_resource_lock_acquire_steal():
    store = new_storage("memkv")
    lock_a = ResourceLock(store, "node-a")
    lock_b = ResourceLock(store, "node-b")
    ea = LeaderElection(lock_a, lease_seconds=0.3, renew_interval=0.05, retry_interval=0.02)
    eb = LeaderElection(lock_b, lease_seconds=0.3, renew_interval=0.05, retry_interval=0.02)
    assert ea.try_acquire_once()
    assert not eb.try_acquire_once()
    assert ea.leader_identity() == "node-a"
    # lease expires without renewal → b steals
    time.sleep(0.35)
    assert eb.try_acquire_once()
    assert eb.leader_identity() == "node-b"
    store.close()


def test_election_campaign_callbacks():
    store = new_storage("memkv")
    store_rev_seen = []
    ea = LeaderElection(
        ResourceLock(store, "node-a"),
        on_started_leading=lambda rev: store_rev_seen.append(rev),
        lease_seconds=0.5,
        renew_interval=0.05,
        retry_interval=0.02,
    )
    ea.campaign()
    assert ea.wait_for_leadership(2.0)
    assert store_rev_seen and store_rev_seen[0] >= 0
    ea.close()
    store.close()


def test_lock_tso_seeds_revision():
    """The lock record carries the engine clock so a new leader resumes
    revisions monotonically (election.go Describe → leader.go:96-107)."""
    store = new_storage("memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=1024))
    b.create(b"/k", b"v")
    assert wait_for_revision(b, 1)
    lock = ResourceLock(store, "node-a")
    rec = lock.create()
    assert rec.tso >= 1
    b.close()
    store.close()
