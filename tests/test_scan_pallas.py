"""Pallas scan kernel vs the jnp kernel (oracle), interpret mode on CPU."""

import numpy as np
import pytest

import jax.numpy as jnp

from kubebrain_tpu.ops import keys as keyops
from kubebrain_tpu.ops.scan import visibility_mask
from kubebrain_tpu.ops import scan_pallas as sp


def build(seed, n_keys=300, revs_max=5):
    rng = np.random.RandomState(seed)
    keys = sorted(
        {b"/reg/" + bytes(rng.randint(97, 123, rng.randint(2, 20), dtype=np.uint8)) for _ in range(n_keys)}
    )
    rows, rev = [], 0
    for k in keys:
        for _ in range(rng.randint(1, revs_max)):
            rev += 1
            rows.append((k, rev, rng.rand() < 0.15))
    chunks, _ = keyops.pack_keys([r[0] for r in rows], 64)
    revs = np.array([r[1] for r in rows], dtype=np.uint64)
    tomb = np.array([r[2] for r in rows])
    return rows, chunks, revs, tomb, rev


@pytest.mark.parametrize("seed", [0, 3])
@pytest.mark.parametrize("bounds", [
    (b"", b""),
    (b"/reg/f", b"/reg/q"),
    (b"/reg/zzzz", b""),
])
def test_pallas_matches_jnp(seed, bounds):
    rows, chunks, revs, tomb, max_rev = build(seed)
    start, end = bounds
    read_rev = max_rev * 2 // 3 or 1

    # oracle: jnp kernel on unpadded rows
    hi, lo = keyops.split_revs(revs)
    want = np.asarray(
        visibility_mask(
            jnp.asarray(chunks), jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(tomb),
            jnp.asarray(np.int32(len(rows))),
            jnp.asarray(keyops.pack_one(start, 64)),
            jnp.asarray(keyops.pack_one(end, 64)),
            jnp.asarray(not end),
            *[jnp.asarray(x[0]) for x in keyops.split_revs(np.array([read_rev], dtype=np.uint64))],
        )
    )

    keys_t, rh31, rl31, tomb8, n = sp.prepare_blocks(chunks, revs, tomb)
    qhi31, qlo31 = sp.split_revs31(np.array([read_rev], dtype=np.uint64))
    got = np.asarray(
        sp.scan_mask_pallas(
            jnp.asarray(keys_t), jnp.asarray(rh31), jnp.asarray(rl31), jnp.asarray(tomb8),
            np.int32(n),
            jnp.asarray(sp.pack_bound_flipped(keyops.pack_one(start, 64))),
            jnp.asarray(sp.pack_bound_flipped(keyops.pack_one(end, 64))),
            np.int32(not end), np.int32(qhi31[0]), np.int32(qlo31[0]),
            interpret=True,
        )
    )[:n]
    assert (got == want).all(), f"mismatch at {np.nonzero(got != want)[0][:10]}"


def test_pallas_cross_tile_carry():
    """A version chain straddling the tile boundary must resolve through the
    carry: the superseded row sits at the end of one tile, its successor at
    the start of the next."""
    tile = sp.LANE_TILE
    n = 2 * tile
    keys = [b"/reg/k%08d" % (i // 2) for i in range(n)]  # 2 revs per key
    chunks, _ = keyops.pack_keys(keys, 64)
    revs = np.arange(1, n + 1, dtype=np.uint64)
    tomb = np.zeros(n, dtype=bool)
    keys_t, rh31, rl31, tomb8, nn = sp.prepare_blocks(chunks, revs, tomb)
    qhi31, qlo31 = sp.split_revs31(np.array([n], dtype=np.uint64))
    got = np.asarray(
        sp.scan_mask_pallas(
            jnp.asarray(keys_t), jnp.asarray(rh31), jnp.asarray(rl31), jnp.asarray(tomb8),
            np.int32(nn),
            jnp.asarray(sp.pack_bound_flipped(keyops.pack_one(b"", 64))),
            jnp.asarray(sp.pack_bound_flipped(keyops.pack_one(b"", 64))),
            np.int32(1), np.int32(qhi31[0]), np.int32(qlo31[0]),
            interpret=True,
        )
    )[:nn]
    # exactly every second row visible (the rev-2 of each key), including the
    # pair straddling the boundary
    want = np.zeros(n, dtype=bool)
    want[1::2] = True
    assert (got == want).all()


def _batch_data(seed, parts=3, n=520):
    """Row-major mirror-layout random data: uint32[P,N,C] sorted per part."""
    rng = np.random.RandomState(seed)
    all_keys, all_revs, all_tomb, nv = [], [], [], []
    rev = 0
    for p in range(parts):
        keys = sorted(
            {b"/reg/%d/" % p + bytes(rng.randint(97, 123, rng.randint(2, 16), dtype=np.uint8))
             for _ in range(n // 3)}
        )
        rows = []
        for k in keys:
            for _ in range(rng.randint(1, 4)):
                rev += 1
                rows.append((k, rev, rng.rand() < 0.2))
        rows = rows[:n]
        chunks, _ = keyops.pack_keys([r[0] for r in rows], 64)
        pad = n - len(rows)
        all_keys.append(np.pad(chunks, ((0, pad), (0, 0))))
        all_revs.append(np.pad(np.array([r[1] for r in rows], dtype=np.uint64), (0, pad)))
        all_tomb.append(np.pad(np.array([r[2] for r in rows]), (0, pad)))
        nv.append(len(rows))
    return (np.stack(all_keys), np.stack(all_revs), np.stack(all_tomb),
            np.array(nv, dtype=np.int32), rev)


@pytest.mark.parametrize("seed", [1, 7])
def test_visibility_mask_batch_matches_vmapped_jnp(seed):
    """The production entry point (row-major [P,N,C] + in-graph layout
    conversion) must equal the jnp kernel exactly — this is the wiring the
    engine runs under --use-pallas."""
    import jax

    keys, revs, tomb, nv, max_rev = _batch_data(seed)
    read_rev = max_rev * 2 // 3 or 1
    hi, lo = keyops.split_revs(revs)
    qhi, qlo = keyops.split_revs(np.array([read_rev], dtype=np.uint64))
    start = keyops.pack_one(b"/reg/", 64)
    end = keyops.pack_one(b"/reg/2/m", 64)
    for unb in (True, False):
        f = lambda k, a, b, t, n: visibility_mask(
            k, a, b, t, n, jnp.asarray(start), jnp.asarray(end),
            jnp.asarray(unb), jnp.asarray(qhi[0]), jnp.asarray(qlo[0]))
        want = np.asarray(jax.vmap(f)(
            jnp.asarray(keys), jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(tomb), jnp.asarray(nv)))
        got = np.asarray(sp.visibility_mask_batch(
            jnp.asarray(keys), jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(tomb),
            jnp.asarray(nv), jnp.asarray(start), jnp.asarray(end), jnp.asarray(unb),
            jnp.asarray(qhi[0]), jnp.asarray(qlo[0]), interpret=True))
        assert (got == want).all()


# --------------------------------------------------- query-batched kernel
def _query_set(max_rev):
    """Distinct bounds + read revisions, including unbounded and empty."""
    return [
        (b"", b"", max_rev),
        (b"/reg/f", b"/reg/q", max_rev * 2 // 3 or 1),
        (b"/reg/c", b"", max_rev // 2 or 1),
        (b"/reg/zzzz", b"", max_rev),          # empty result range
        (b"/reg/a", b"/reg/zz", max_rev // 3 or 1),
    ]


@pytest.mark.parametrize("seed", [0, 5])
def test_scan_mask_pallas_q_matches_single(seed):
    """ONE query-batched launch over Q distinct (bounds, read_rev) queries
    must equal Q single-query launches bit for bit — including Q=1 (the
    tentpole's 'Q=1 stays bit-identical' contract)."""
    rows, chunks, revs, tomb, max_rev = build(seed)
    keys_t, rh31, rl31, tomb8, n = sp.prepare_blocks(chunks, revs, tomb)
    queries = _query_set(max_rev)
    for nq in (1, len(queries)):
        qs = queries[:nq]
        starts = np.stack([sp.pack_bound_flipped(keyops.pack_one(s, 64)) for s, _, _ in qs])
        ends = np.stack([sp.pack_bound_flipped(keyops.pack_one(e, 64)) for _, e, _ in qs])
        unb = np.array([int(not e) for _, e, _ in qs], dtype=np.int32)
        qh, ql = sp.split_revs31(np.array([r for _, _, r in qs], dtype=np.uint64))
        got = np.asarray(sp.scan_mask_pallas_q(
            jnp.asarray(keys_t), jnp.asarray(rh31), jnp.asarray(rl31),
            jnp.asarray(tomb8), np.int32(n), jnp.asarray(starts),
            jnp.asarray(ends), jnp.asarray(unb), jnp.asarray(qh),
            jnp.asarray(ql), interpret=True))
        assert got.shape[0] == nq
        for qi, (s, e, r) in enumerate(qs):
            qh1, ql1 = sp.split_revs31(np.array([r], dtype=np.uint64))
            want = np.asarray(sp.scan_mask_pallas(
                jnp.asarray(keys_t), jnp.asarray(rh31), jnp.asarray(rl31),
                jnp.asarray(tomb8), np.int32(n), jnp.asarray(starts[qi]),
                jnp.asarray(ends[qi]), np.int32(unb[qi]),
                np.int32(qh1[0]), np.int32(ql1[0]), interpret=True))
            assert (got[qi] == want).all(), (nq, qi)


def test_scan_mask_pallas_q_cross_tile_and_query_carry():
    """Version chains straddling the tile boundary must resolve through the
    carry for EVERY query of the batch — and the carry must not leak
    across the query axis (each query's last tile ignores it)."""
    tile = sp.LANE_TILE
    n = 2 * tile
    keys = [b"/reg/k%08d" % (i // 2) for i in range(n)]  # 2 revs per key
    chunks, _ = keyops.pack_keys(keys, 64)
    revs = np.arange(1, n + 1, dtype=np.uint64)
    tomb = np.zeros(n, dtype=bool)
    keys_t, rh31, rl31, tomb8, nn = sp.prepare_blocks(chunks, revs, tomb)
    # q0 sees every row (head read); q1 reads at rev n/2 (only the first
    # half's chains resolved); q2 is an empty range
    read_revs = np.array([n, n // 2, n], dtype=np.uint64)
    bounds = [(b"", b""), (b"", b""), (b"/reg/z", b"")]
    starts = np.stack([sp.pack_bound_flipped(keyops.pack_one(s, 64)) for s, _ in bounds])
    ends = np.stack([sp.pack_bound_flipped(keyops.pack_one(e, 64)) for _, e in bounds])
    unb = np.array([1, 1, 1], dtype=np.int32)
    qh, ql = sp.split_revs31(read_revs)
    got = np.asarray(sp.scan_mask_pallas_q(
        jnp.asarray(keys_t), jnp.asarray(rh31), jnp.asarray(rl31),
        jnp.asarray(tomb8), np.int32(nn), jnp.asarray(starts),
        jnp.asarray(ends), jnp.asarray(unb), jnp.asarray(qh), jnp.asarray(ql),
        interpret=True))
    want0 = np.zeros(n, dtype=bool)
    want0[1::2] = True  # rev-2 of each key, incl. the pair straddling tiles
    assert (got[0] == want0).all()
    # oracle the mid-history query through the single kernel
    qh1, ql1 = sp.split_revs31(np.array([n // 2], dtype=np.uint64))
    want1 = np.asarray(sp.scan_mask_pallas(
        jnp.asarray(keys_t), jnp.asarray(rh31), jnp.asarray(rl31),
        jnp.asarray(tomb8), np.int32(nn), jnp.asarray(starts[1]),
        jnp.asarray(ends[1]), np.int32(1), np.int32(qh1[0]), np.int32(ql1[0]),
        interpret=True))
    assert (got[1] == want1).all()
    assert not got[2].any()  # empty range, despite q1's carry state


@pytest.mark.parametrize("seed", [2])
def test_visibility_mask_batch_cached_q_matches_vmapped_jnp(seed):
    """The query-batched cached-mirror entry (what `_dev_mask_batch` runs
    under --use-pallas) must equal the vmapped jnp kernel per query."""
    import jax

    from kubebrain_tpu.ops.scan import visibility_mask

    keys, revs, tomb, nv, max_rev = _batch_data(seed)
    revs64 = np.asarray(revs, dtype=np.uint64)
    keys_t, rh31, rl31, tomb8, n = sp.prepare_mirror(keys, revs64, tomb)
    hi, lo = keyops.split_revs(revs)
    queries = [
        (b"/reg/", b"/reg/2/m", max_rev * 2 // 3 or 1),
        (b"", b"", max_rev),
        (b"/reg/1/", b"/reg/1/zzz", max_rev // 2 or 1),
        (b"/reg/2/", b"", max_rev),
    ]
    starts = np.stack([keyops.pack_one(s, 64) for s, _, _ in queries])
    ends = np.stack([keyops.pack_one(e, 64) for _, e, _ in queries])
    unb = np.array([not e for _, e, _ in queries])
    qh, ql = keyops.split_revs(np.array([r for _, _, r in queries], dtype=np.uint64))
    got = np.asarray(sp.visibility_mask_batch_cached_q(
        jnp.asarray(keys_t), jnp.asarray(rh31.reshape(keys.shape[0], -1)),
        jnp.asarray(rl31.reshape(keys.shape[0], -1)), jnp.asarray(tomb8),
        jnp.asarray(nv), jnp.asarray(starts), jnp.asarray(ends),
        jnp.asarray(unb.astype(np.int32)), jnp.asarray(qh), jnp.asarray(ql),
        n=n, interpret=True))
    assert got.shape == (len(queries), keys.shape[0], n)
    for qi, (s, e, r) in enumerate(queries):
        qh1, ql1 = keyops.split_revs(np.array([r], dtype=np.uint64))
        f = lambda k, a, b, t, m: visibility_mask(
            k, a, b, t, m, jnp.asarray(starts[qi]), jnp.asarray(ends[qi]),
            jnp.asarray(bool(unb[qi])), jnp.asarray(qh1[0]), jnp.asarray(ql1[0]))
        want = np.asarray(jax.vmap(f)(
            jnp.asarray(keys), jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(tomb), jnp.asarray(nv)))
        assert (got[qi] == want).all(), qi


def test_wired_engine_pallas_differential():
    """Full-engine differential: the same op sequence through --use-pallas
    and the jnp kernel must produce identical lists/counts/streams (VERDICT
    r2 missing #2: flag-gated wiring + equal-output test)."""
    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.parallel.mesh import make_mesh
    from kubebrain_tpu.storage import new_storage

    mesh = make_mesh(n_devices=1)
    backends = []
    for use_pallas in (False, True):
        store = new_storage("tpu", inner="memkv", mesh=mesh, use_pallas=use_pallas)
        b = Backend(store, BackendConfig(event_ring_capacity=4096, watch_cache_capacity=4096))
        b.scanner._host_limit_threshold = 0
        b.scanner._merge_threshold = 8
        # pin the kernel explicitly: ambient KB_PALLAS_INTERPRET / a TPU
        # backend would otherwise change what this test exercises
        b.scanner._scan_kernel = "pallas_interpret" if use_pallas else "jnp"
        b.scanner._kernel_mesh = mesh if use_pallas else None
        backends.append((store, b))
    assert backends[1][1].scanner._scan_kernel != "jnp"

    rng = np.random.RandomState(42)
    snap_revs = []
    for i in range(40):
        k = b"/registry/pods/p%03d" % rng.randint(0, 25)
        prefer_delete = rng.rand() < 0.3
        for _s, b in backends:
            try:
                b.create(k, b"v%d" % i)
            except Exception:
                kv = b.get(k)
                if prefer_delete:
                    b.delete(k)
                else:
                    b.update(k, b"v%d'" % i, kv.revision)
        if i % 10 == 5:
            snap_revs.append(backends[0][1].current_revision())

    b_jnp, b_pal = backends[0][1], backends[1][1]
    assert b_jnp.current_revision() == b_pal.current_revision()
    for rev in snap_revs + [b_jnp.current_revision()]:
        r1 = b_jnp.list_(b"/registry/", b"/registry0", revision=rev)
        r2 = b_pal.list_(b"/registry/", b"/registry0", revision=rev)
        assert [(kv.key, kv.value, kv.revision) for kv in r1.kvs] == \
               [(kv.key, kv.value, kv.revision) for kv in r2.kvs]
    c1, _ = b_jnp.count(b"/registry/", b"/registry0")
    c2, _ = b_pal.count(b"/registry/", b"/registry0")
    assert c1 == c2
    s1 = [kv.key for batch in b_jnp.scanner.range_stream(b"/", b"", b_jnp.current_revision()) for kv in batch]
    s2 = [kv.key for batch in b_pal.scanner.range_stream(b"/", b"", b_pal.current_revision()) for kv in batch]
    assert s1 == s2
    for s, b in backends:
        b.close(); s.close()


def test_wired_engine_pallas_sharded_multidevice():
    """The Pallas path on the 8-device mesh goes through shard_map (per-shard
    pallas_call, no replication) and must still equal the jnp engine."""
    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.parallel.mesh import make_mesh
    from kubebrain_tpu.storage import new_storage

    mesh = make_mesh()  # all 8 virtual CPU devices on the 'part' axis
    backends = []
    for use_pallas in (False, True):
        store = new_storage("tpu", inner="memkv", mesh=mesh, use_pallas=use_pallas)
        b = Backend(store, BackendConfig(event_ring_capacity=4096, watch_cache_capacity=4096))
        b.scanner._host_limit_threshold = 0
        b.scanner._merge_threshold = 4
        b.scanner._scan_kernel = "pallas_interpret" if use_pallas else "jnp"
        b.scanner._kernel_mesh = mesh if use_pallas else None
        backends.append((store, b))
    for i in range(30):
        k = b"/registry/nodes/n%03d" % i
        for _s, b in backends:
            b.create(k, b"v%d" % i)
    b_jnp, b_pal = backends[0][1], backends[1][1]
    r1 = b_jnp.list_(b"/registry/", b"/registry0")
    r2 = b_pal.list_(b"/registry/", b"/registry0")
    assert [(kv.key, kv.value, kv.revision) for kv in r1.kvs] == \
           [(kv.key, kv.value, kv.revision) for kv in r2.kvs]
    assert len(r2.kvs) == 30
    c1, _ = b_jnp.count(b"/registry/", b"/registry0")
    c2, _ = b_pal.count(b"/registry/", b"/registry0")
    assert c1 == c2 == 30
    for s, b in backends:
        b.close(); s.close()
