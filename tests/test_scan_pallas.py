"""Pallas scan kernel vs the jnp kernel (oracle), interpret mode on CPU."""

import numpy as np
import pytest

import jax.numpy as jnp

from kubebrain_tpu.ops import keys as keyops
from kubebrain_tpu.ops.scan import visibility_mask
from kubebrain_tpu.ops import scan_pallas as sp


def build(seed, n_keys=300, revs_max=5):
    rng = np.random.RandomState(seed)
    keys = sorted(
        {b"/reg/" + bytes(rng.randint(97, 123, rng.randint(2, 20), dtype=np.uint8)) for _ in range(n_keys)}
    )
    rows, rev = [], 0
    for k in keys:
        for _ in range(rng.randint(1, revs_max)):
            rev += 1
            rows.append((k, rev, rng.rand() < 0.15))
    chunks, _ = keyops.pack_keys([r[0] for r in rows], 64)
    revs = np.array([r[1] for r in rows], dtype=np.uint64)
    tomb = np.array([r[2] for r in rows])
    return rows, chunks, revs, tomb, rev


@pytest.mark.parametrize("seed", [0, 3])
@pytest.mark.parametrize("bounds", [
    (b"", b""),
    (b"/reg/f", b"/reg/q"),
    (b"/reg/zzzz", b""),
])
def test_pallas_matches_jnp(seed, bounds):
    rows, chunks, revs, tomb, max_rev = build(seed)
    start, end = bounds
    read_rev = max_rev * 2 // 3 or 1

    # oracle: jnp kernel on unpadded rows
    hi, lo = keyops.split_revs(revs)
    want = np.asarray(
        visibility_mask(
            jnp.asarray(chunks), jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(tomb),
            jnp.asarray(np.int32(len(rows))),
            jnp.asarray(keyops.pack_one(start, 64)),
            jnp.asarray(keyops.pack_one(end, 64)),
            jnp.asarray(not end),
            *[jnp.asarray(x[0]) for x in keyops.split_revs(np.array([read_rev], dtype=np.uint64))],
        )
    )

    keys_t, rh31, rl31, tomb8, n = sp.prepare_blocks(chunks, revs, tomb)
    qhi31, qlo31 = sp.split_revs31(np.array([read_rev], dtype=np.uint64))
    got = np.asarray(
        sp.scan_mask_pallas(
            jnp.asarray(keys_t), jnp.asarray(rh31), jnp.asarray(rl31), jnp.asarray(tomb8),
            np.int32(n),
            jnp.asarray(sp.pack_bound_flipped(keyops.pack_one(start, 64))),
            jnp.asarray(sp.pack_bound_flipped(keyops.pack_one(end, 64))),
            np.int32(not end), np.int32(qhi31[0]), np.int32(qlo31[0]),
            interpret=True,
        )
    )[:n]
    assert (got == want).all(), f"mismatch at {np.nonzero(got != want)[0][:10]}"


def test_pallas_cross_tile_carry():
    """A version chain straddling the tile boundary must resolve through the
    carry: the superseded row sits at the end of one tile, its successor at
    the start of the next."""
    tile = sp.LANE_TILE
    n = 2 * tile
    keys = [b"/reg/k%08d" % (i // 2) for i in range(n)]  # 2 revs per key
    chunks, _ = keyops.pack_keys(keys, 64)
    revs = np.arange(1, n + 1, dtype=np.uint64)
    tomb = np.zeros(n, dtype=bool)
    keys_t, rh31, rl31, tomb8, nn = sp.prepare_blocks(chunks, revs, tomb)
    qhi31, qlo31 = sp.split_revs31(np.array([n], dtype=np.uint64))
    got = np.asarray(
        sp.scan_mask_pallas(
            jnp.asarray(keys_t), jnp.asarray(rh31), jnp.asarray(rl31), jnp.asarray(tomb8),
            np.int32(nn),
            jnp.asarray(sp.pack_bound_flipped(keyops.pack_one(b"", 64))),
            jnp.asarray(sp.pack_bound_flipped(keyops.pack_one(b"", 64))),
            np.int32(1), np.int32(qhi31[0]), np.int32(qlo31[0]),
            interpret=True,
        )
    )[:nn]
    # exactly every second row visible (the rev-2 of each key), including the
    # pair straddling the boundary
    want = np.zeros(n, dtype=bool)
    want[1::2] = True
    assert (got == want).all()
