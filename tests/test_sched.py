"""Scheduler semantics (ISSUE 2 acceptance):

- coalescing preserves per-request ``read_revision`` visibility — results
  byte-identical to the unscheduled path (CPU fallback over the generic
  scanner, no TPU required);
- priority inversion does not occur under a saturated low-priority flood
  (high-priority p99 stays bounded at 10x queue oversubscription);
- shed requests carry the etcd ``ResourceExhausted`` wire error, and the
  shed/queue-depth counters are visible on /metrics.
"""

import queue
import threading
import time
import urllib.request

import grpc
import pytest

from kubebrain_tpu.backend import Backend, BackendConfig
from kubebrain_tpu.sched import (
    Lane,
    RequestScheduler,
    SchedConfig,
    SchedOverloadError,
    classify,
    ensure_scheduler,
)
from kubebrain_tpu.storage import new_storage

from test_etcd_server import EtcdClient, free_port


# ---------------------------------------------------------------- lanes
def test_classify_lanes():
    assert classify(b"/registry/leases/kube-system/x", b"", 0) is Lane.SYSTEM
    assert classify(b"/registry/masterleases/1.2.3.4", b"", 0) is Lane.SYSTEM
    assert classify(b"/registry/pods/", b"/registry/pods0", 500) is Lane.NORMAL
    assert classify(b"/registry/pods/", b"/registry/pods0", 0) is Lane.BACKGROUND
    assert classify(b"/registry/pods/", b"/registry/pods0",
                    count_only=True) is Lane.NORMAL
    # empty end at the scheduler means UNBOUNDED (single-key reads never
    # reach it): the Snapshot whole-keyspace dump is background traffic
    assert classify(b"", b"", 0) is Lane.BACKGROUND
    assert classify(b"/registry/pods/a", b"", limit=10) is Lane.NORMAL


# --------------------------------------------------- generic submit layer
def test_submit_runs_and_returns():
    s = RequestScheduler(None, SchedConfig(depth=2))
    try:
        assert s.submit(lambda: 41 + 1) == 42
    finally:
        s.close()


def test_submit_propagates_exceptions():
    s = RequestScheduler(None, SchedConfig(depth=2))
    try:
        with pytest.raises(ValueError):
            s.submit(lambda: (_ for _ in ()).throw(ValueError("boom")))
    finally:
        s.close()


def test_queue_full_sheds_immediately():
    s = RequestScheduler(None, SchedConfig(depth=1, queue_limit=2))
    release = threading.Event()
    try:
        s.submit_async(release.wait, Lane.NORMAL)  # occupies the one slot
        time.sleep(0.1)  # let the dispatcher move it to a worker
        # the dispatcher can hold at most one popped request in hand, so
        # by the 4th filler the 2-slot queue must overflow
        sheds = 0
        for _ in range(4):
            try:
                s.submit_async(lambda: None, Lane.NORMAL)
            except SchedOverloadError:
                sheds += 1
        assert sheds >= 1
        assert s.shed_counts[Lane.NORMAL] == sheds
    finally:
        release.set()
        s.close()


def test_deadline_shed_on_stale_requests():
    s = RequestScheduler(None, SchedConfig(depth=1, shed_ms=50.0))
    release = threading.Event()
    try:
        s.submit_async(release.wait, Lane.NORMAL)
        time.sleep(0.1)
        stale = s.submit_async(lambda: "ran", Lane.NORMAL)
        time.sleep(0.2)  # let it age past shed_ms while the slot is held
        release.set()
        with pytest.raises(SchedOverloadError):
            stale.wait(5.0)
        assert s.shed_counts[Lane.NORMAL] >= 1
    finally:
        release.set()
        s.close()


def test_lane_queue_round_robin_fair_after_drain_cycles():
    """Regression: drain/refill cycles must not accumulate stale service-
    order entries that skew round-robin toward long-lived clients."""
    from kubebrain_tpu.sched.scheduler import _LaneQueue, _Request

    lq = _LaneQueue()

    def mk(c):
        return _Request(lambda: None, Lane.NORMAL, c, None)

    for _ in range(5):  # client A drains repeatedly before B shows up
        lq.push(mk("A"))
        assert lq.pop().client == "A"
    for _ in range(6):
        lq.push(mk("A"))
        lq.push(mk("B"))
    got = [lq.pop().client for _ in range(12)]
    assert got == ["A", "B"] * 6, got  # strict alternation, no A-burst
    assert lq.pop() is None
    assert not lq.order and not lq.clients and lq.size == 0


# ------------------------------------------------------------- priority
def test_no_priority_inversion_under_background_flood():
    """A SYSTEM request enqueued behind a saturated BACKGROUND flood must
    dispatch as soon as a slot frees — at most one head-of-line background
    request (already popped by the dispatcher) runs ahead of it."""
    s = RequestScheduler(None, SchedConfig(depth=1, queue_limit=256))
    done: list[str] = []
    lock = threading.Lock()

    def record(tag):
        def fn():
            time.sleep(0.01)
            with lock:
                done.append(tag)
        return fn

    release = threading.Event()
    try:
        s.submit_async(release.wait, Lane.NORMAL)  # plug the single slot
        time.sleep(0.1)
        bg = [s.submit_async(record(f"bg{i}"), Lane.BACKGROUND)
              for i in range(30)]
        sys_req = s.submit_async(record("system"), Lane.SYSTEM)
        release.set()
        sys_req.wait(10.0)
        for r in bg:
            r.wait(10.0)
        # dispatcher may have one background request in hand when the
        # system request arrives; everything else must queue behind it
        assert "system" in done[:2], done[:5]
    finally:
        release.set()
        s.close()


def test_overload_high_priority_p99_bounded_at_10x():
    """10x queue oversubscription on the background lane: background work
    sheds, while SYSTEM requests keep a bounded p99."""
    qlimit = 16
    s = RequestScheduler(None, SchedConfig(depth=2, queue_limit=qlimit,
                                           shed_ms=30_000.0))
    stop = threading.Event()
    shed = 0
    shed_lock = threading.Lock()
    admitted = []
    try:
        # 10x oversubscription: keep the background queue pinned at its
        # limit for the whole measurement window
        def flood():
            nonlocal shed
            while not stop.is_set():
                try:
                    req = s.submit_async(lambda: time.sleep(0.005),
                                         Lane.BACKGROUND)
                    with shed_lock:
                        admitted.append(req)
                except SchedOverloadError:
                    with shed_lock:
                        shed += 1
        flooders = [threading.Thread(target=flood, daemon=True)
                    for _ in range(4)]
        for t in flooders:
            t.start()
        time.sleep(0.2)
        lat = []
        for _ in range(20):
            t0 = time.monotonic()
            s.submit(lambda: None, Lane.SYSTEM)
            lat.append(time.monotonic() - t0)
        stop.set()
        for t in flooders:
            t.join(5.0)
        lat.sort()
        p99 = lat[-1]
        # bounded: a slot frees every ~5ms; generous 2s bound absorbs CI
        # noise while still ruling out queued-behind-the-flood (the flood
        # alone is > 160 x 5ms deep at all times)
        assert p99 < 2.0, f"system p99 {p99:.3f}s under background flood"
        assert shed > len(admitted), (shed, len(admitted))
        assert s.shed_counts[Lane.BACKGROUND] == shed
    finally:
        stop.set()
        s.close()


# ------------------------------------------------- backend-level parity
def _build_backend():
    store = new_storage("memkv")
    backend = Backend(store, BackendConfig(event_ring_capacity=8192))
    return store, backend


def _snapshot(res):
    """Byte-string fingerprint of a RangeResult (order included)."""
    out = [b"%d|%d|%d" % (res.revision, res.count, int(res.more))]
    for kv in res.kvs:
        out.append(kv.key + b"\x00" + kv.value + b"\x00%d" % kv.revision)
    return b"\xff".join(out)


def test_scheduled_results_byte_identical_randomized():
    """Randomized Range workloads over the CPU fallback path: scheduled
    and unscheduled results are byte-identical (revision pinned and
    unpinned; the store is quiescent during comparison)."""
    import random

    rng = random.Random(20260803)
    store, backend = _build_backend()
    sched = ensure_scheduler(backend, SchedConfig(depth=4))
    try:
        keys = []
        checkpoints = []
        for i in range(60):
            k = b"/registry/%s/obj-%04d" % (
                rng.choice([b"pods", b"services", b"secrets"]), i)
            keys.append(k)
            backend.create(k, b"v0-%d" % i)
        checkpoints.append(backend.current_revision())
        for k in rng.sample(keys, 30):
            rec = backend._read_rev_record(k)
            backend.update(k, b"v1-" + k, rec[0])
        checkpoints.append(backend.current_revision())
        for k in rng.sample(keys, 10):
            try:
                backend.delete(k)
            except Exception:
                pass
        checkpoints.append(backend.current_revision())

        bounds = sorted(rng.sample(keys, 20)) + [b"/registry/", b"/registry0"]
        workloads = []
        for _ in range(40):
            a, b = rng.choice(bounds), rng.choice(bounds)
            if a > b:
                a, b = b, a
            if a == b:
                b = a + b"\xff"
            rev = rng.choice([0] + checkpoints)
            limit = rng.choice([0, 0, 7, 100])
            workloads.append((a, b, rev, limit))

        results: dict[int, bytes] = {}

        def run(i, w):
            results[i] = _snapshot(sched.list_(*w))

        threads = [threading.Thread(target=run, args=(i, w))
                   for i, w in enumerate(workloads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        for i, w in enumerate(workloads):
            assert results[i] == _snapshot(backend.list_(*w)), w
        # counts match too
        for a, b, rev, _ in workloads[:10]:
            assert sched.count(a, b, rev) == backend.count(a, b, rev)
    finally:
        backend.close()
        store.close()


def test_coalescing_preserves_read_revision_visibility():
    """Identical queued requests coalesce into one execution; requests at
    different explicit revisions never share results."""
    store, backend = _build_backend()
    sched = ensure_scheduler(backend, SchedConfig(depth=1, queue_limit=256))
    try:
        for i in range(20):
            backend.create(b"/registry/co/k%03d" % i, b"v0")
        r1 = backend.current_revision()
        for i in range(20):
            rec = backend._read_rev_record(b"/registry/co/k%03d" % i)
            backend.update(b"/registry/co/k%03d" % i, b"v1", rec[0])
        r2 = backend.current_revision()

        release = threading.Event()
        sched.submit_async(release.wait, Lane.SYSTEM)  # plug the slot
        time.sleep(0.1)

        outs: dict[int, object] = {}
        revs = [r1, r2, r1, r2, r1, r2, r1, r1]

        def run(i):
            outs[i] = sched.list_(b"/registry/co/", b"/registry/co0",
                                  revs[i], 0)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(revs))]
        for t in threads:
            t.start()
        time.sleep(0.3)  # all enqueued against the plugged slot
        release.set()
        for t in threads:
            t.join(20.0)

        assert sched.coalesced > 0  # identical queued requests merged
        for i, rev in enumerate(revs):
            expect = backend.list_(b"/registry/co/", b"/registry/co0", rev, 0)
            assert _snapshot(outs[i]) == _snapshot(expect), rev
        # r1 results really differ from r2 (the visibility check has teeth)
        assert _snapshot(outs[0]) != _snapshot(outs[1])
    finally:
        release.set()
        backend.close()
        store.close()


# ------------------------------------------------------- wire-level shed
@pytest.fixture()
def overloaded_endpoint():
    """A live endpoint whose backend list path is artificially slow and
    whose scheduler queue is tiny — Range floods must shed."""
    from kubebrain_tpu.endpoint import Endpoint, EndpointConfig
    from kubebrain_tpu.metrics.prom import PrometheusMetrics
    from kubebrain_tpu.server import Server
    from kubebrain_tpu.server.service import SingleNodePeerService

    store = new_storage("memkv")
    backend = Backend(store, BackendConfig(event_ring_capacity=8192))
    metrics = PrometheusMetrics()
    ensure_scheduler(backend, SchedConfig(depth=1, queue_limit=2,
                                          shed_ms=30_000.0), metrics=metrics)
    slow_list = backend.list_

    def slowed(*a, **kw):
        time.sleep(0.15)
        return slow_list(*a, **kw)

    backend.list_ = slowed
    peers = SingleNodePeerService(backend)
    server = Server(backend, peers, metrics)
    cport, info = free_port(), free_port()
    ep = Endpoint(server, metrics, EndpointConfig(
        host="127.0.0.1", client_port=cport,
        peer_port=free_port(), info_port=info,
    ))
    ep.run()
    yield f"127.0.0.1:{cport}", info, backend
    ep.close()
    backend.close()
    store.close()


def test_shed_returns_resource_exhausted_on_wire(overloaded_endpoint):
    target, info_port, backend = overloaded_endpoint
    from kubebrain_tpu.proto import rpc_pb2

    c = EtcdClient(target)
    for i in range(5):
        c.create(b"/registry/pods/p%02d" % i, b"v")

    codes: list = []
    details: list = []

    def one_list(i):
        try:
            # distinct limits => distinct coalesce keys: identical requests
            # would legitimately merge into one execution and never shed
            c.range_(rpc_pb2.RangeRequest(
                key=b"/registry/pods/", range_end=b"/registry/pods0",
                limit=i + 1))
            codes.append("ok")
        except grpc.RpcError as e:
            codes.append(e.code())
            details.append(e.details())

    threads = [threading.Thread(target=one_list, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)

    shed = [x for x in codes if x == grpc.StatusCode.RESOURCE_EXHAUSTED]
    assert shed, codes  # 16 concurrent vs depth 1 + queue 2: must shed
    assert any("etcdserver: too many requests" in d for d in details), details
    assert "ok" in codes  # admitted requests still served

    body = urllib.request.urlopen(
        f"http://127.0.0.1:{info_port}/metrics", timeout=10
    ).read().decode()
    assert "kb_sched_shed_total" in body, body[:2000]
    assert "kb_sched_queue_depth" in body
    assert "kb_sched_inflight" in body
    sched = backend._kb_scheduler
    assert sum(sched.shed_counts.values()) >= len(shed)
