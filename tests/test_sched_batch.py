"""Query-batched device scan (ISSUE 5 acceptance):

- batched vs sequential execution is BYTE-IDENTICAL across randomized
  ranges, read revisions, limits, and live delta overlays (the scheduler
  is a throughput layer, never a semantics layer — same bar the coalescing
  tests hold);
- Count rides the same kernel launch as Range (one `_dev_mask_batch`
  dispatch per batch, zero single-query dispatches);
- per-query demux: a compacted read revision fails its own query, not the
  batch;
- batching does not starve the SYSTEM lane at 10x background overload;
- the batched overlay probes (`_host_visible_batch`) equal the per-key
  `_host_visible` oracle.

Runs entirely on the CPU fallback (jnp kernel over the tpu engine's memkv
inner store; one pallas-interpret differential for the kernel wiring).
"""

import random
import threading
import time

import pytest

from kubebrain_tpu.backend import Backend, BackendConfig
from kubebrain_tpu.backend.errors import CompactedError
from kubebrain_tpu.parallel.mesh import make_mesh
from kubebrain_tpu.sched import Lane, SchedConfig, ensure_scheduler
from kubebrain_tpu.storage import new_storage


def _snapshot(res):
    """Byte-string fingerprint of a RangeResult (order included)."""
    out = [b"%d|%d|%d" % (res.revision, res.count, int(res.more))]
    for kv in res.kvs:
        out.append(kv.key + b"\x00" + kv.value + b"\x00%d" % kv.revision)
    return b"\xff".join(out)


def _tpu_backend(n_devices=1, scan_kernel="jnp", host_limit_threshold=0,
                 merge_threshold=10**9):
    """A tpu-engine backend over memkv: device path for every unpaged read,
    delta kept as a live overlay (huge merge threshold) unless merged."""
    mesh = make_mesh(n_devices=n_devices)
    store = new_storage("tpu", inner="memkv", mesh=mesh)
    backend = Backend(store, BackendConfig(event_ring_capacity=8192,
                                           watch_cache_capacity=4096))
    sc = backend.scanner
    sc._host_limit_threshold = host_limit_threshold
    sc._merge_threshold = merge_threshold
    if scan_kernel != "jnp":
        sc._scan_kernel = scan_kernel
        sc._kernel_mesh = mesh
    return store, backend


def _populate(backend, rng, n_keys=50, n_ops=120):
    """Create/update/delete churn; returns (keys, revision checkpoints)."""
    keys = [b"/registry/%s/obj-%04d" % (
        rng.choice([b"pods", b"services", b"secrets"]), i)
        for i in range(n_keys)]
    checkpoints = []
    for k in keys:
        backend.create(k, b"v0-" + k)
    checkpoints.append(backend.current_revision())
    for _ in range(n_ops):
        k = rng.choice(keys)
        try:
            kv = backend.get(k)
            if rng.random() < 0.2:
                backend.delete(k, kv.revision)
            else:
                backend.update(k, b"v%d" % rng.randrange(10**6), kv.revision)
        except Exception:
            try:
                backend.create(k, b"re-" + k)
            except Exception:
                pass
        if rng.random() < 0.1:
            checkpoints.append(backend.current_revision())
    checkpoints.append(backend.current_revision())
    return keys, checkpoints


def _workloads(rng, keys, checkpoints, n=40):
    bounds = sorted(rng.sample(keys, min(16, len(keys)))) + \
        [b"/registry/", b"/registry0"]
    out = []
    for _ in range(n):
        a, b = rng.choice(bounds), rng.choice(bounds)
        if a > b:
            a, b = b, a
        if a == b:
            b = a + b"\xff"
        rev = rng.choice([0] + checkpoints)
        if rng.random() < 0.25:
            out.append(("count", a, b, rev))
        else:
            # limit 3 exercises the host small-page fallback inside a batch
            out.append(("list", a, b, rev, rng.choice([0, 0, 3, 25, 500])))
    return out


# ---------------------------------------------------------------- property
def test_batched_vs_sequential_byte_identical_randomized():
    """The tentpole property: randomized Range/Count workloads executed as
    scheduler batches (forced formation: plugged single slot) are
    byte-identical to sequential unscheduled execution — with a LIVE delta
    overlay (mirror published mid-churn, never merged)."""
    rng = random.Random(20260803)
    store, backend = _tpu_backend()
    sc = backend.scanner
    sched = ensure_scheduler(backend, SchedConfig(depth=1, queue_limit=512,
                                                  batch=8))
    try:
        keys, checkpoints = _populate(backend, rng)
        sc.publish()  # mirror snapshot here...
        for k in rng.sample(keys, 20):  # ...then more churn -> live overlay
            try:
                kv = backend.get(k)
                if rng.random() < 0.3:
                    backend.delete(k, kv.revision)
                else:
                    backend.update(k, b"overlay", kv.revision)
            except Exception:
                try:
                    backend.create(k, b"overlay-new")
                except Exception:
                    pass
        checkpoints.append(backend.current_revision())
        assert len(sc._delta) > 0, "test needs a live overlay"

        workloads = _workloads(rng, keys, checkpoints, n=48)
        sc._host_limit_threshold = 4  # limit-3 lists take the host path

        release = threading.Event()
        sched.submit_async(release.wait, Lane.SYSTEM)  # plug the one slot
        time.sleep(0.15)
        results: dict[int, object] = {}

        def run(i, w):
            try:
                if w[0] == "count":
                    results[i] = sched.count(w[1], w[2], w[3], client=f"c{i%5}")
                else:
                    results[i] = sched.list_(w[1], w[2], w[3], w[4],
                                             client=f"c{i%5}")
            except BaseException as e:  # surfaced to the assert below
                results[i] = e
        threads = [threading.Thread(target=run, args=(i, w))
                   for i, w in enumerate(workloads)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # everything enqueued against the plugged slot
        release.set()
        for t in threads:
            t.join(60.0)

        assert sched.batched > 0, "no batches formed"
        assert len(sc._delta) > 0, "overlay merged away mid-test"
        for i, w in enumerate(workloads):
            assert not isinstance(results[i], BaseException), (w, results[i])
            if w[0] == "count":
                assert results[i] == backend.count(w[1], w[2], w[3]), w
            else:
                want = backend.list_(w[1], w[2], w[3], w[4])
                assert _snapshot(results[i]) == _snapshot(want), w
    finally:
        backend.close()
        store.close()


# ----------------------------------------------- one launch for the batch
def test_count_rides_the_same_launch_as_range():
    """A mixed Range+Count batch must cost exactly ONE `_dev_mask_batch`
    dispatch and ZERO single-query `_dev_mask` dispatches."""
    rng = random.Random(7)
    store, backend = _tpu_backend()
    sc = backend.scanner
    try:
        _populate(backend, rng, n_keys=30, n_ops=40)
        sc.publish()
        head = backend.current_revision()
        calls = {"batch": 0, "single": 0}
        orig_batch, orig_single = sc._dev_mask_batch, sc._dev_mask

        def count_batch(*a, **kw):
            calls["batch"] += 1
            return orig_batch(*a, **kw)

        def count_single(*a, **kw):
            calls["single"] += 1
            return orig_single(*a, **kw)
        sc._dev_mask_batch, sc._dev_mask = count_batch, count_single

        specs = [
            ("range", b"/registry/pods/", b"/registry/pods0", head, 0),
            ("count", b"/registry/", b"/registry0", head),
            ("range", b"/registry/", b"/registry0", head, 0),
            ("count", b"/registry/pods/", b"/registry/pods0", head),
        ]
        got = sc.scan_batch(specs)
        assert calls == {"batch": 1, "single": 0}, calls

        sc._dev_mask_batch, sc._dev_mask = orig_batch, orig_single
        for spec, g in zip(specs, got):
            if spec[0] == "count":
                assert g == sc.count(spec[1], spec[2], spec[3]), spec
            else:
                kvs, more = sc.range_(spec[1], spec[2], spec[3], spec[4])
                assert g[1] == more
                assert [(kv.key, kv.value, kv.revision) for kv in g[0]] == \
                       [(kv.key, kv.value, kv.revision) for kv in kvs], spec
    finally:
        backend.close()
        store.close()


def test_batched_pallas_interpret_matches_jnp_engine():
    """The pallas-interpret batched path (what a real TPU runs compiled)
    against the jnp engine on the same op sequence — scan_batch results
    must match across kernels, on the multi-device mesh (shard_map)."""
    rng = random.Random(11)
    stores = []
    for kernel in ("jnp", "pallas_interpret"):
        s, b = _tpu_backend(n_devices=None, scan_kernel=kernel)
        stores.append((s, b))
    try:
        for _s, b in stores:
            brng = random.Random(3)
            _populate(b, brng, n_keys=24, n_ops=30)
            b.scanner.publish()
        b_jnp, b_pal = stores[0][1], stores[1][1]
        assert b_jnp.current_revision() == b_pal.current_revision()
        head = b_jnp.current_revision()
        specs = [
            ("range", b"/registry/", b"/registry0", head, 0),
            ("count", b"/registry/", b"/registry0", head),
            ("range", b"/registry/pods/", b"/registry/pods0", head, 10),
        ]
        r1 = b_jnp.scanner.scan_batch(specs)
        r2 = b_pal.scanner.scan_batch(specs)
        assert r1[1] == r2[1]
        for a, b_ in ((r1[0], r2[0]), (r1[2], r2[2])):
            assert a[1] == b_[1]
            assert [(kv.key, kv.value, kv.revision) for kv in a[0]] == \
                   [(kv.key, kv.value, kv.revision) for kv in b_[0]]
    finally:
        for s, b in stores:
            b.close()
            s.close()


# ------------------------------------------------------------------ demux
def test_per_query_error_demux_compacted_revision():
    """One compacted read revision inside a batch fails only its own
    waiter; the rest of the batch serves normally."""
    rng = random.Random(5)
    store, backend = _tpu_backend()
    sched = ensure_scheduler(backend, SchedConfig(depth=1, queue_limit=256,
                                                  batch=8))
    try:
        keys, checkpoints = _populate(backend, rng, n_keys=20, n_ops=40)
        old = checkpoints[0]
        assert checkpoints[-1] > old
        backend.compact(checkpoints[-1])
        head = backend.current_revision()

        # backend-level: the batch executor demuxes the exception element
        out = backend.list_batch([
            ("list", b"/registry/", b"/registry0", head, 0),
            ("list", b"/registry/", b"/registry0", old, 0),
            ("count", b"/registry/", b"/registry0", old),
        ])
        assert not isinstance(out[0], BaseException)
        assert isinstance(out[1], CompactedError)
        assert isinstance(out[2], CompactedError)

        # scheduler-level: the waiter of the compacted query raises, the
        # good query (batched into the same slot) still answers
        release = threading.Event()
        sched.submit_async(release.wait, Lane.SYSTEM)
        time.sleep(0.1)
        results: dict[str, object] = {}

        def good():
            results["good"] = sched.list_(b"/registry/", b"/registry0", head, 0)

        def bad():
            try:
                sched.list_(b"/registry/", b"/registry0", old, 0)
                results["bad"] = None
            except CompactedError as e:
                results["bad"] = e
        threads = [threading.Thread(target=good), threading.Thread(target=bad)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        release.set()
        for t in threads:
            t.join(30.0)
        assert isinstance(results["bad"], CompactedError)
        assert _snapshot(results["good"]) == \
               _snapshot(backend.list_(b"/registry/", b"/registry0", head, 0))
    finally:
        backend.close()
        store.close()


# ------------------------------------------------------- overlay probing
def test_host_visible_batch_matches_per_key_oracle():
    """`_host_visible_batch` (one searchsorted pass per partition) must
    agree with the per-key `_host_visible` binary search for every key —
    present, deleted, superseded, and absent."""
    rng = random.Random(13)
    store, backend = _tpu_backend()
    sc = backend.scanner
    try:
        keys, checkpoints = _populate(backend, rng, n_keys=40, n_ops=80)
        sc.publish()
        mirror = sc._mirror
        probes = keys + [b"/registry/absent/x%d" % i for i in range(5)]
        for rev in (checkpoints[0], checkpoints[len(checkpoints) // 2],
                    checkpoints[-1]):
            got = sc._host_visible_batch(mirror, probes, rev)
            want = [sc._host_visible(mirror, uk, rev) for uk in probes]
            assert got == want, rev
        assert any(got) and not all(got)  # the check has teeth both ways
    finally:
        backend.close()
        store.close()


def test_count_overlay_correction_batched():
    """count() with a live overlay (adds, deletes, supersedes) must match
    a freshly-published mirror's count at every checkpoint revision."""
    rng = random.Random(17)
    store, backend = _tpu_backend()
    sc = backend.scanner
    try:
        keys, _ = _populate(backend, rng, n_keys=30, n_ops=30)
        sc.publish()
        mid = backend.current_revision()
        for k in rng.sample(keys, 12):  # overlay churn on the published mirror
            try:
                kv = backend.get(k)
                if rng.random() < 0.4:
                    backend.delete(k, kv.revision)
                else:
                    backend.update(k, b"ov", kv.revision)
            except Exception:
                try:
                    backend.create(k, b"ov-new")
                except Exception:
                    pass
        head = backend.current_revision()
        assert len(sc._delta) > 0
        got_mid = sc.count(b"/registry/", b"/registry0", mid)
        got_head = sc.count(b"/registry/", b"/registry0", head)
        sc.publish()  # merge the overlay; pure-mirror counts as oracle
        assert sc.count(b"/registry/", b"/registry0", mid) == got_mid
        assert sc.count(b"/registry/", b"/registry0", head) == got_head
    finally:
        backend.close()
        store.close()


# ------------------------------------------------------------- starvation
def test_batching_does_not_starve_system_lane_at_10x_overload():
    """10x queue oversubscription of batchable BACKGROUND lists: SYSTEM
    reads must keep a bounded p99 (they ride the next freed slot — batch
    draining pops in strict lane-priority order), and batches must
    actually form under the flood."""
    rng = random.Random(23)
    store, backend = _tpu_backend()
    qlimit = 16
    sched = ensure_scheduler(backend, SchedConfig(depth=2, queue_limit=qlimit,
                                                  shed_ms=30_000.0, batch=8))
    try:
        _populate(backend, rng, n_keys=30, n_ops=30)
        backend.scanner.publish()
        for i in range(3):
            backend.create(b"/registry/leases/kube-system/l%d" % i, b"x")
        # warm the jit caches (single-dispatch path + the pow2 batched Q
        # shapes) so the timed loop measures scheduling, not compilation
        sched.list_(b"/registry/leases/", b"/registry/leases0", 0, 10)
        backend.list_batch([
            ("list", b"/registry/", b"/registry0", 0, 1000 + i)
            for i in range(8)
        ])
        stop = threading.Event()
        shed = 0
        shed_lock = threading.Lock()
        from kubebrain_tpu.sched import SchedOverloadError

        def flood():
            # async floods (no per-request wait) keep the background queue
            # pinned at its limit — 10x oversubscription like test_sched's
            nonlocal shed
            i = 0
            pending = []
            while not stop.is_set():
                i += 1
                a, b = b"/registry/", b"/registry0"
                # distinct limits -> distinct coalesce keys: every request
                # is its own batchable unit
                lim = 1000 + (i % 64)
                try:
                    pending.append(sched.submit_async(
                        lambda lim=lim: backend.list_(a, b, 0, lim),
                        Lane.BACKGROUND, client=f"f{i % 4}",
                        key=("list", a, b, 0, lim, i),
                        bargs=("list", a, b, 0, lim)))
                except SchedOverloadError:
                    with shed_lock:
                        shed += 1
                if len(pending) >= 64:
                    try:
                        pending[0].wait(30.0)
                    except SchedOverloadError:
                        pass
                    del pending[0]
            for r in pending:
                try:
                    r.wait(30.0)
                except SchedOverloadError:
                    pass
        flooders = [threading.Thread(target=flood, daemon=True)
                    for _ in range(4)]
        for t in flooders:
            t.start()
        time.sleep(0.3)
        lat = []
        for _ in range(20):
            t0 = time.monotonic()
            sched.list_(b"/registry/leases/", b"/registry/leases0", 0, 10)
            lat.append(time.monotonic() - t0)
        stop.set()
        for t in flooders:
            t.join(30.0)
        lat.sort()
        assert lat[-1] < 2.0, f"system p99 {lat[-1]:.3f}s under batched flood"
        assert sched.batched > 0, "flood never formed a batch"
        assert shed > 0, "flood never oversubscribed the queue"
    finally:
        backend.close()
        store.close()
