"""Concurrency property tests for the event pipeline.

SURVEY §5 (race detection): the reference relies on design invariants
instead of a race detector — single sequencer, revision-indexed ring,
panic-on-wrap. The Python analogue is property tests: under concurrent mixed
workloads with conflicts,

1. every dealt revision is committed exactly once, in order (no gaps, no
   stalls);
2. the watch event stream is strictly increasing and *replaying it* onto an
   empty dict reproduces exactly the server's final state;
3. the ring never wraps (writers crash loudly rather than corrupt).
"""

import queue
import threading

import numpy as np
import pytest

from kubebrain_tpu.backend import (
    Backend,
    BackendConfig,
    Verb,
    WatchEvent,
    wait_for_revision,
)
from kubebrain_tpu.storage import new_storage


def test_concurrent_churn_event_replay_equals_state():
    store = new_storage("memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=65536, watch_cache_capacity=65536))
    wid, q = b.watch(b"/")
    rng = np.random.RandomState(3)
    N_THREADS, OPS = 6, 120
    keys = [b"/reg/k%02d" % i for i in range(25)]
    errors = []

    def worker(seed):
        r = np.random.RandomState(seed)
        for _ in range(OPS):
            k = keys[r.randint(len(keys))]
            try:
                op = r.rand()
                if op < 0.5:
                    b.create(k, b"v%d" % r.randint(1000))
                elif op < 0.8:
                    kv = b.get(k)
                    b.update(k, b"u%d" % r.randint(1000), kv.revision)
                else:
                    kv = b.get(k)
                    b.delete(k, kv.revision)
            except Exception:
                pass  # expected conflicts under contention

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    dealt = b.tso.dealt()
    # P1: every dealt revision commits (sequencer drains completely)
    assert wait_for_revision(b, dealt, timeout=10)
    assert b.current_revision() == dealt

    # P2: event stream strictly increasing; replay == final state
    events = []
    while True:
        try:
            batch = q.get(timeout=0.5)
        except queue.Empty:
            break
        if batch is None:
            break
        events.extend(batch)
    revs = [e.revision for e in events]
    assert revs == sorted(revs) and len(revs) == len(set(revs))
    replay = {}
    for e in events:
        if e.verb == Verb.DELETE:
            replay.pop(e.key, None)
        else:
            replay[e.key] = e.value
    res = b.list_(b"/reg/", b"/reg0")
    server_state = {kv.key: kv.value for kv in res.kvs}
    assert replay == server_state
    b.close()
    store.close()


def test_ring_wrap_crashes_loudly():
    """A sequencer that cannot keep up must fail writers, not corrupt the
    stream (reference panics, txn.go:287-290)."""
    store = new_storage("memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=8))
    # wedge the sequencer by freezing its condition variable consumer:
    # simulate with direct notifies beyond capacity
    for i in range(1, 9):
        b._notify(WatchEvent(revision=100 + i, valid=False))
    with pytest.raises(RuntimeError, match="ring wrapped"):
        b._notify(WatchEvent(revision=100 + 9, valid=False))
    b.close()
    store.close()
