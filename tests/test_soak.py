"""Chaos soak: leader killed under concurrent write load, twice.

Safety property: every ACKNOWLEDGED write survives with its acknowledged
revision (stateless nodes over a durable engine — the reference's core
claim). Liveness: writers make progress after each failover.
"""

import threading
import time

import pytest

from kubebrain_tpu.storage import new_storage

from test_multinode import Node


def test_failover_under_load_no_acked_writes_lost():
    import math

    from kubebrain_tpu.lincheck import History

    store = new_storage("memkv")
    nodes = [Node(store) for _ in range(3)]
    history = History()  # record() is one list.append: thread-safe under GIL
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not any(n.peers.is_leader() for n in nodes):
            time.sleep(0.05)

        acked: dict[bytes, int] = {}
        acked_lock = threading.Lock()
        stop = threading.Event()
        live_nodes = list(nodes)

        def writer(w):
            i = 0
            while not stop.is_set():
                key = b"/registry/soak/w%02d-%05d" % (w, i)
                wrote = False
                for n in list(live_nodes):
                    t0 = time.monotonic()
                    try:
                        resp = n.client.create(key, b"v")
                    except Exception:
                        # node died mid-call: outcome unknown — the op may
                        # or may not have landed (Jepsen :info op)
                        history.record(w, "create", key, t0, math.inf,
                                       value=b"v", ok=None)
                        continue
                    if resp.succeeded:
                        rev = resp.responses[0].response_put.header.revision
                        with acked_lock:
                            acked[key] = rev
                        history.record(w, "create", key, t0, time.monotonic(),
                                       value=b"v", ok=True, rev=rev)
                        wrote = True
                        break
                    else:
                        # keys are writer-unique: a conflict proves this
                        # writer's own earlier unknown-outcome create landed
                        # — move on instead of livelocking on the key
                        crev = 0
                        try:
                            crev = resp.responses[0].response_range.kvs[0].mod_revision
                        except (IndexError, AttributeError):
                            pass
                        history.record(w, "create", key, t0, time.monotonic(),
                                       value=b"v", ok=False, err="conflict",
                                       conflict_rev=crev)
                        wrote = True
                        break
                if wrote:
                    i += 1
                else:
                    time.sleep(0.02)

        writers = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
        for t in writers:
            t.start()

        for _round in range(2):  # kill the leader twice
            time.sleep(1.0)
            leader = next((n for n in live_nodes if n.peers.is_leader()), None)
            if leader is not None:
                live_nodes.remove(leader)
                leader.close()
            deadline = time.time() + 10
            while time.time() < deadline and not any(
                n.peers.is_leader() for n in live_nodes
            ):
                time.sleep(0.05)
            assert any(n.peers.is_leader() for n in live_nodes), "no failover"

        time.sleep(1.0)
        stop.set()
        for t in writers:
            t.join(timeout=30)
        # the history fold below assumes writer quiescence: a straggler
        # completing a create after the survivor snapshot would make the
        # folded not-found read a spurious linearizability violation
        assert not any(t.is_alive() for t in writers), "writer failed to stop"

        assert len(acked) > 50, f"writers made little progress: {len(acked)}"
        survivor = next(n for n in live_nodes if n.peers.is_leader())
        from kubebrain_tpu.proto import rpc_pb2

        r = survivor.client.range_(
            rpc_pb2.RangeRequest(key=b"/registry/soak/", range_end=b"/registry/soak0")
        )
        server = {kv.key: kv.mod_revision for kv in r.kvs}
        missing = [k for k in acked if k not in server]
        assert not missing, f"lost {len(missing)} acknowledged writes: {missing[:5]}"
        wrong_rev = [k for k, rv in acked.items() if server[k] != rv]
        assert not wrong_rev, f"acked revision changed for {wrong_rev[:5]}"

        # linearizability: fold the survivor's final state into the history
        # as completed reads, then check the whole concurrent run — acked
        # creates must be readable at their revision, unknown-outcome ops
        # may have landed or not, revisions must respect real time
        # (reference README.md:30-34 lists Jepsen as TODO; lincheck.py)
        t_end = time.monotonic()
        seen_keys = set()
        for kv in r.kvs:
            seen_keys.add(bytes(kv.key))
            history.record(99, "get", bytes(kv.key), t_end, t_end + 0.001,
                           value=bytes(kv.value), ok=True, rev=kv.mod_revision)
        for op in list(history.ops):
            if op.key not in seen_keys and op.kind == "create":
                # key absent from the final state: a completed not-found read
                history.record(99, "get", op.key, t_end, t_end + 0.001, ok=False)
                seen_keys.add(op.key)
        res = history.check()  # strict: budget exhaustion fails, not passes
        assert res["ok"], f"soak history not linearizable: {res['violation']}"
        assert res["ops"] > 100
        print(f"[soak-lincheck] ops={res['ops']} keys={res['keys']} "
              f"nodes={res['nodes_searched']} max_key={res['max_key_nodes']}")
    finally:
        for n in nodes:
            try:
                n.close()
            except Exception:
                pass
        store.close()


def test_realtime_revision_ordering():
    """Linearizability smoke for writes (the reference lists Jepsen as TODO,
    README.md:30-34): if op A's response completes before op B begins, A's
    revision must be lower — revisions must respect real time across
    concurrent clients."""
    import bisect

    from kubebrain_tpu.backend import Backend, BackendConfig

    store = new_storage("native")
    b = Backend(store, BackendConfig(event_ring_capacity=65536))
    records = []  # (t_start, t_end, revision)
    lock = threading.Lock()

    def writer(w):
        for i in range(200):
            t0 = time.monotonic()
            rev = b.create(b"/lin/w%02d-%04d" % (w, i), b"v")
            t1 = time.monotonic()
            with lock:
                records.append((t0, t1, rev))

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    revs = [r for _, _, r in records]
    assert len(revs) == len(set(revs)), "revision handed out twice"
    # real-time order: for every pair where A ended before B started,
    # rev_A < rev_B. Check efficiently: sort by start; walk keeping the max
    # revision among ops that END before the current start.
    by_start = sorted(records)
    ends = sorted((t1, rev) for _, t1, rev in records)
    end_times = [e[0] for e in ends]
    max_rev_until = []
    mx = 0
    for _, rev in ends:
        mx = max(mx, rev)
        max_rev_until.append(mx)
    violations = 0
    for t0, _, rev in by_start:
        idx = bisect.bisect_left(end_times, t0) - 1
        if idx >= 0 and max_rev_until[idx] >= rev:
            violations += 1
    assert violations == 0, f"{violations} real-time ordering violations"
    b.close()
    store.close()
