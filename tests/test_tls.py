"""TLS endpoint tests: secure gRPC + HTTPS control plane with generated
certs (reference: pkg/util/auth testdata + endpoint_test.go TestRunEndpoint
hitting /health over http and https)."""

import datetime
import os
import ssl
import urllib.request

import grpc
import pytest

try:
    import cryptography  # noqa: F401  -- cert generation dependency
except ImportError:
    pytest.skip(
        "cryptography not installed in this image (needed to generate the "
        "self-signed test certs)", allow_module_level=True,
    )

from kubebrain_tpu.cli import build_endpoint, build_parser
from kubebrain_tpu.proto import rpc_pb2

from test_etcd_server import EtcdClient, free_port


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """Self-signed server cert for 127.0.0.1 (the gen-certs.sh analogue)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    d = tmp_path_factory.mktemp("certs")
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "kubebrain-tpu-test")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([
                x509.DNSName("localhost"),
                x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1")),
            ]),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_file = os.path.join(d, "server.crt")
    key_file = os.path.join(d, "server.key")
    with open(cert_file, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_file, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ))
    return cert_file, key_file


def test_secure_grpc_and_https(certs):
    cert_file, key_file = certs
    port, peer, info = free_port(), free_port(), free_port()
    args = build_parser().parse_args([
        "--single-node", "--storage", "memkv", "--host", "127.0.0.1",
        "--client-port", str(port), "--peer-port", str(peer), "--info-port", str(info),
        "--cert-file", cert_file, "--key-file", key_file,
    ])
    endpoint, backend, store = build_endpoint(args)
    endpoint.config.insecure = False  # secure-only mode
    endpoint.run()
    try:
        with open(cert_file, "rb") as f:
            creds = grpc.ssl_channel_credentials(root_certificates=f.read())
        ch = grpc.secure_channel(f"127.0.0.1:{port}", creds)
        txn = ch.unary_unary(
            "/etcdserverpb.KV/Txn",
            request_serializer=rpc_pb2.TxnRequest.SerializeToString,
            response_deserializer=rpc_pb2.TxnResponse.FromString,
        )
        req = rpc_pb2.TxnRequest()
        c = req.compare.add()
        c.result = rpc_pb2.Compare.EQUAL
        c.target = rpc_pb2.Compare.MOD
        c.key = b"/tls/k"
        c.mod_revision = 0
        req.success.add().request_put.CopyFrom(rpc_pb2.PutRequest(key=b"/tls/k", value=b"v"))
        resp = txn(req, timeout=5)
        assert resp.succeeded
        ch.close()

        # plaintext client must NOT work in secure-only mode
        insecure = EtcdClient(f"127.0.0.1:{port}")
        with pytest.raises(grpc.RpcError):
            insecure.create(b"/tls/x", b"v")
        insecure.close()

        # HTTPS control plane
        ctx = ssl.create_default_context(cafile=cert_file)
        ctx.check_hostname = False
        with urllib.request.urlopen(
            f"https://127.0.0.1:{peer}/health", timeout=5, context=ctx
        ) as resp:
            assert b"true" in resp.read()
    finally:
        endpoint.close()
        backend.close()
        store.close()
