"""TPU block-store engine tests: the same MVCC semantics through
``--storage=tpu`` (device mirror + delta overlay), differential-tested
against the generic engine — the multi-backend matrix of the reference
(backend_test.go:52-88) extended to the device path.

Runs on the 8-device virtual CPU mesh (conftest.py).
"""

import numpy as np
import pytest

from kubebrain_tpu.backend import Backend, BackendConfig, wait_for_revision
from kubebrain_tpu.storage import new_storage
from kubebrain_tpu.storage.errors import KeyNotFoundError


@pytest.fixture
def tb():
    store = new_storage("tpu", inner="memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=4096, watch_cache_capacity=4096))
    # low thresholds so tests exercise the device path, not the host fallback
    b.scanner._host_limit_threshold = 0
    b.scanner._merge_threshold = 8
    yield b
    b.close()
    store.close()


def test_basic_crud_via_device(tb):
    K = b"/registry/pods/default/nginx"
    r1 = tb.create(K, b"v1")
    res = tb.list_(b"/registry/", b"/registry0")
    assert [(kv.key, kv.value, kv.revision) for kv in res.kvs] == [(K, b"v1", r1)]
    r2 = tb.update(K, b"v2", r1)
    res = tb.list_(b"/registry/", b"/registry0")
    assert [(kv.value, kv.revision) for kv in res.kvs] == [(b"v2", r2)]
    # snapshot read hits the device path too
    res = tb.list_(b"/registry/", b"/registry0", revision=r1)
    assert [(kv.value, kv.revision) for kv in res.kvs] == [(b"v1", r1)]
    tb.delete(K)
    res = tb.list_(b"/registry/", b"/registry0")
    assert res.kvs == []


def test_mirror_merge_and_overlay(tb):
    # 20 writes with merge_threshold 8: some rows come from the merged
    # mirror, some from the delta overlay
    revs = {}
    for i in range(20):
        k = b"/registry/pods/p%02d" % i
        revs[k] = tb.create(k, b"v%d" % i)
    res = tb.list_(b"/registry/pods/", b"/registry/pods0")
    assert len(res.kvs) == 20
    assert [kv.key for kv in res.kvs] == sorted(revs)
    cnt, _ = tb.count(b"/registry/pods/", b"/registry/pods0")
    assert cnt == 20
    # delete half; count adjusts through overlay + device
    for i in range(0, 20, 2):
        tb.delete(b"/registry/pods/p%02d" % i)
    cnt, _ = tb.count(b"/registry/pods/", b"/registry/pods0")
    assert cnt == 10


def test_limit_uses_host_path_consistently(tb):
    for i in range(12):
        tb.create(b"/registry/x%02d" % i, b"v")
    tb.scanner._host_limit_threshold = 1024  # re-enable host fallback
    res = tb.list_(b"/registry/", b"/registry0", limit=5)
    assert len(res.kvs) == 5 and res.more
    assert [kv.key for kv in res.kvs] == [b"/registry/x%02d" % i for i in range(5)]


def test_compact_on_device(tb):
    K = b"/registry/pods/a"
    r1 = tb.create(K, b"v1")
    r2 = tb.update(K, b"v2", r1)
    KD = b"/registry/pods/del"
    rd = tb.create(KD, b"bye")
    rdel, _ = tb.delete(KD)
    assert wait_for_revision(tb, rdel)
    done = tb.compact(rdel)
    assert done == rdel
    from kubebrain_tpu import coder

    # superseded + tombstoned rows physically gone from the host store
    inner = tb.store._inner
    with pytest.raises(KeyNotFoundError):
        inner.get(coder.encode_object_key(K, r1))
    with pytest.raises(KeyNotFoundError):
        inner.get(coder.encode_revision_key(KD))
    # and the mirror still answers correctly
    res = tb.list_(b"/registry/", b"/registry0")
    assert [(kv.key, kv.value) for kv in res.kvs] == [(K, b"v2")]
    cnt, _ = tb.count(b"/registry/", b"/registry0")
    assert cnt == 1


def test_differential_vs_generic_engine():
    """Random workload on both engines; every read must agree.
    (The reference runs identical table-driven cases across engines;
    randomized differential testing covers more interleavings.)"""
    rng = np.random.RandomState(7)
    g_store = new_storage("memkv")
    g = Backend(g_store, BackendConfig(event_ring_capacity=8192))
    t_store = new_storage("tpu", inner="memkv")
    t = Backend(t_store, BackendConfig(event_ring_capacity=8192))
    t.scanner._host_limit_threshold = 0
    t.scanner._merge_threshold = 16

    keys = [b"/reg/k%02d" % i for i in range(30)]
    live_rev: dict[bytes, int] = {}
    checkpoints = []
    for step in range(300):
        k = keys[rng.randint(len(keys))]
        op = rng.rand()
        for be in (g, t):
            try:
                if k not in live_rev:
                    r = be.create(k, b"val%d" % step)
                elif op < 0.6:
                    r = be.update(k, b"val%d" % step, live_rev[k])
                else:
                    r, _ = be.delete(k, live_rev[k])
            except Exception as e:
                r = ("err", type(e).__name__)
            results = r
        # engines share revision sequence determinism: same op order
        if k not in live_rev:
            live_rev[k] = results if isinstance(results, int) else live_rev.get(k, 0)
        elif op < 0.6:
            live_rev[k] = results
        else:
            live_rev.pop(k, None)
        if step % 50 == 49:
            checkpoints.append(g.current_revision())

    def snapshot(be, rev=0):
        res = be.list_(b"/reg/", b"/reg0", revision=rev)
        return [(kv.key, kv.value, kv.revision) for kv in res.kvs]

    assert g.current_revision() == t.current_revision()
    assert snapshot(g) == snapshot(t)
    for cp in checkpoints:
        assert snapshot(g, cp) == snapshot(t, cp), f"diverged at rev {cp}"
    cg, _ = g.count(b"/reg/", b"/reg0")
    ct, _ = t.count(b"/reg/", b"/reg0")
    assert cg == ct
    for be in (g, t):
        be.close()
    g_store.close()
    t_store.close()


def test_partitions_align_with_mesh(tb):
    for i in range(40):
        tb.create(b"/registry/pods/p%03d" % i, b"v")
    tb.scanner.publish()
    parts = tb.get_partitions(b"/registry/", b"/registry0")
    # mirror partitions (8 CPU devices) surface as storage partitions
    assert len(parts) >= 2
    assert parts[0].left == b"/registry/"
    assert parts[-1].right == b"/registry0"


def test_range_stream_device_path(tb):
    """Streaming list goes through the device index path and matches the
    non-streaming result, including delta-overlay insertions/tombstones."""
    for i in range(30):
        tb.create(b"/registry/rs/p%03d" % i, b"v%d" % i)
    tb.scanner.publish()
    # leave fresh rows in the delta: an insert and a delete overlay
    tb.scanner._merge_threshold = 10**9
    tb.create(b"/registry/rs/extra", b"fresh")
    tb.delete(b"/registry/rs/p005")
    rev, stream = tb.list_by_stream(b"/registry/rs/", b"/registry/rs0")
    streamed = [kv for batch in stream for kv in batch]
    plain = tb.list_(b"/registry/rs/", b"/registry/rs0").kvs
    assert [(kv.key, kv.value) for kv in streamed] == [(kv.key, kv.value) for kv in plain]
    keys = [kv.key for kv in streamed]
    assert keys == sorted(keys)
    assert b"/registry/rs/extra" in keys and b"/registry/rs/p005" not in keys


def test_differential_with_compaction_and_recreate():
    """Deep differential: deletes, recreates over tombstones, and periodic
    compaction on both engines; snapshots and final state must agree and
    stay correct after GC."""
    rng = np.random.RandomState(11)
    g_store = new_storage("memkv")
    g = Backend(g_store, BackendConfig(event_ring_capacity=16384))
    t_store = new_storage("tpu", inner="memkv")
    t = Backend(t_store, BackendConfig(event_ring_capacity=16384))
    t.scanner._host_limit_threshold = 0
    t.scanner._merge_threshold = 32

    keys = [b"/reg/dc/k%02d" % i for i in range(20)]
    live: dict[bytes, int] = {}
    for step in range(400):
        k = keys[rng.randint(len(keys))]
        op = rng.rand()
        res = None
        for be in (g, t):
            try:
                if k not in live:
                    res = be.create(k, b"s%d" % step)
                elif op < 0.5:
                    res = be.update(k, b"s%d" % step, live[k])
                else:
                    res, _ = be.delete(k, live[k])
            except Exception:
                res = None
        if res is not None:
            if k not in live:
                live[k] = res
            elif op < 0.5:
                live[k] = res
            else:
                live.pop(k, None)
        if step % 97 == 96:
            target = g.current_revision() - 10
            if target > 0:
                assert wait_for_revision(g, g.tso.dealt())
                assert wait_for_revision(t, t.tso.dealt())
                dg = g.compact(target)
                dt_ = t.compact(target)
                assert dg == dt_, f"compact diverged {dg} != {dt_}"

    def snap(be):
        res = be.list_(b"/reg/dc/", b"/reg/dc0")
        return [(kv.key, kv.value, kv.revision) for kv in res.kvs]

    assert snap(g) == snap(t)
    cg, _ = g.count(b"/reg/dc/", b"/reg/dc0")
    ct, _ = t.count(b"/reg/dc/", b"/reg/dc0")
    assert cg == ct == len(live)
    # every live key readable with its exact revision on both engines
    for k, rv in live.items():
        assert g.get(k).revision == rv and t.get(k).revision == rv
    for be in (g, t):
        be.close()
    g_store.close()
    t_store.close()


def test_incremental_merge_reuses_clean_shards():
    """VERDICT r1 weak #4: delta merges must not republish every partition.
    After an incremental merge, clean partitions' device buffers are the
    SAME buffers (no re-upload); only dirty partitions change."""
    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.storage import new_storage

    store = new_storage("tpu", inner="memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=8192, watch_cache_capacity=1024))
    sc = b.scanner
    sc._merge_threshold = 50
    # populate a wide keyspace so partitions have distinct ranges
    for i in range(400):
        b.create(b"/registry/im/k%04d" % i, b"v")
    sc.publish()
    m0 = sc._mirror
    P = m0.partitions
    assert P >= 2

    def shard_ptrs(mirror):
        return [s.data.unsafe_buffer_pointer()
                for s in mirror.keys_dev.addressable_shards]

    ptrs0 = shard_ptrs(m0)
    # write a burst of keys that all land in the LAST partition's range
    for i in range(60):
        b.create(b"/registry/im/zzz%04d" % i, b"v2")
    sc.publish()
    m1 = sc._mirror
    assert m1 is not m0
    ptrs1 = shard_ptrs(m1)
    changed = [p for p in range(P) if ptrs1[p] != ptrs0[p]]
    assert changed, "the dirty partition must re-upload"
    assert len(changed) < P, (
        f"only dirty partitions may re-upload; all {P} changed"
    )
    # correctness after the in-place merge
    res = b.list_(b"/registry/im/", b"/registry/im0")
    assert len(res.kvs) == 460
    assert res.kvs[-1].key == b"/registry/im/zzz0059"
    cnt, _ = b.count(b"/registry/im/", b"/registry/im0")
    assert cnt == 460
    b.close()
    store.close()


def test_incremental_merge_overflow_grows_in_stored_domain():
    """A partition overflowing its padded capacity GROWS the stored-domain
    arrays (memcpy + republish) instead of taking the full decode →
    re-dictionary → re-partition host rebuild (docs/writes.md merge
    policy) — and reads stay correct."""
    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.storage import new_storage

    store = new_storage("tpu", inner="memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=16384, watch_cache_capacity=1024))
    sc = b.scanner
    sc._merge_threshold = 100
    for i in range(50):
        b.create(b"/registry/of/k%04d" % i, b"v")
    sc.publish()
    cap0 = sc._mirror.keys_host.shape[1]
    # burst big enough to blow past the padded capacity of one partition
    for i in range(800):
        b.create(b"/registry/of/m%04d" % i, b"v")
    sc.publish()
    assert sc.full_rebuild_total == 0, \
        "capacity overflow must grow in the stored domain, not full-rebuild"
    assert sc.merge_count > 0 and sc.merge_rows_total > 0
    assert sc._mirror.keys_host.shape[1] > cap0, "capacity must have grown"
    res = b.list_(b"/registry/of/", b"/registry/of0")
    assert len(res.kvs) == 850
    b.close()
    store.close()


def test_delta_index_overlay_snapshot_semantics():
    """Overlay respects read revisions: an old snapshot read must not see
    newer delta versions (per-key revision list bisected by read_rev)."""
    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.storage import new_storage

    store = new_storage("tpu", inner="memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=4096, watch_cache_capacity=1024))
    b.scanner._merge_threshold = 10_000  # keep everything in the delta
    r1 = b.create(b"/registry/sn/a", b"v1")
    b.scanner.publish()  # mirror at r1
    r2 = b.update(b"/registry/sn/a", b"v2", r1)
    r3 = b.update(b"/registry/sn/a", b"v3", r2)
    res_old = b.list_(b"/registry/sn/", b"/registry/sn0", revision=r2)
    assert res_old.kvs[0].value == b"v2"
    res_new = b.list_(b"/registry/sn/", b"/registry/sn0")
    assert res_new.kvs[0].value == b"v3" and res_new.kvs[0].revision == r3
    b.close()
    store.close()


def test_pull_victim_indices_adaptive_branches(tb):
    """Both sides of the shard-local two-phase transfer (pull victim
    indices vs pull survivor indices) must rebuild the exact same victim
    identities as the device mask. A bulk compact of long version chains
    has few survivors; an incremental compact has few victims — force each
    branch and differential-check against the directly-pulled mask."""
    from unittest import mock

    # long chains: 6 keys x 30 revisions -> compacting makes most rows victims
    revs = {}
    for i in range(6):
        k = b"/registry/pods/c%d" % i
        r = tb.create(k, b"v0")
        for j in range(29):
            r = tb.update(k, b"v%d" % (j + 1), r)
        revs[k] = r
    last = max(revs.values())
    assert wait_for_revision(tb, last)

    sc = tb.scanner
    sc._ensure_published(full=True)
    pulled = []
    orig = type(sc)._pull_victim_indices

    def spy(self, mask_dev, mirror):
        out = orig(self, mask_dev, mirror)
        # the differential: the per-partition victim identities must equal
        # the device mask pulled directly (identities, not just counts)
        mask_h = np.asarray(mask_dev).astype(bool)
        for p in range(mask_h.shape[0]):
            nv = int(mirror.n_valid[p])
            want = np.nonzero(mask_h[p, :nv])[0]
            got = out.get(p, np.empty(0, dtype=np.int64))
            assert np.array_equal(np.asarray(got), want), (p, got, want)
        pulled.append(out)
        return out

    with mock.patch.object(type(sc), "_pull_victim_indices", spy):
        tb.compact(last)
    assert pulled, "compact did not route through the two-phase pull"
    n_bulk = sum(len(v) for v in pulled[-1].values())
    # bulk compact of 30-rev chains: victims outnumber survivors
    assert n_bulk > (6 * 30) // 2

    # incremental compact right after: almost no victims -> victim branch
    r2 = tb.update(b"/registry/pods/c0", b"vz", revs[b"/registry/pods/c0"])
    assert wait_for_revision(tb, r2)
    pulled.clear()
    with mock.patch.object(type(sc), "_pull_victim_indices", spy):
        tb.compact(r2)
    assert pulled and sum(len(v) for v in pulled[-1].values()) <= 2

    # state still correct after both branches
    res = tb.list_(b"/registry/", b"/registry0")
    assert len(res.kvs) == 6
    assert {kv.key: kv.value for kv in res.kvs}[b"/registry/pods/c0"] == b"vz"
