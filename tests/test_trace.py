"""End-to-end request tracing: span trees with device-time attribution,
W3C traceparent propagation over the real gRPC surface, /debug/traces +
/debug/profile, the kb_rpc_stage_seconds histogram, watch-path lag
metrics, and auto pipeline depth (--sched-depth 0) from the measured
dispatch-RTT EWMA."""

import json
import urllib.request

import grpc
import pytest

from kubebrain_tpu.cli import build_endpoint, build_parser
from kubebrain_tpu.proto import rpc_pb2
from kubebrain_tpu.sched.scheduler import (
    AUTO_DEPTH_DEFAULT,
    AUTO_DEPTH_MAX,
    AUTO_DEPTH_MIN,
    RequestScheduler,
    SchedConfig,
)
from kubebrain_tpu.trace import (
    TRACER,
    Tracer,
    make_traceparent,
    parse_traceparent,
)

from test_etcd_server import EtcdClient, free_port


# ------------------------------------------------------------- traceparent
def test_traceparent_roundtrip():
    tp = make_traceparent()
    parsed = parse_traceparent(tp)
    assert parsed is not None
    trace_id, span_id = parsed
    assert len(trace_id) == 32 and len(span_id) == 16
    # bytes headers (grpc metadata values may be bytes) parse too
    assert parse_traceparent(tp.encode()) == parsed


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-zz-xx-01",
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace id
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
])
def test_traceparent_rejects_invalid(bad):
    assert parse_traceparent(bad) is None


def test_traceparent_continues_ambient_trace():
    t = Tracer()
    with t.span("outer") as sp:
        tp = make_traceparent()
        assert parse_traceparent(tp)[0] == sp.trace_id


# ------------------------------------------------------- tracer mechanics
def test_span_ring_bounded_and_slow_log():
    t = Tracer(capacity=4, slow_ms=0.0)  # slow log off
    for i in range(10):
        with t.span(f"op-{i}"):
            pass
    snap = t.snapshot()
    assert len(snap["traces"]) == 4
    assert snap["traces"][-1]["name"] == "op-9"
    assert snap["slow"] == []

    slow = Tracer(capacity=4, slow_ms=0.001)  # everything is "slow"
    with slow.span("slowpoke"):
        with slow.stage("device_compute"):
            import time

            time.sleep(0.002)
    snap = slow.snapshot()
    assert [s["name"] for s in snap["slow"]] == ["slowpoke"]
    stages = snap["traces"][0]["stages"]
    assert stages[0]["stage"] == "device_compute"
    assert stages[0]["duration_ms"] >= 1.0


def test_span_records_error_and_nested_spans_collapse():
    t = Tracer()
    with pytest.raises(ValueError):
        with t.span("boom"):
            raise ValueError("nope")
    assert "ValueError" in t.snapshot()["traces"][-1]["error"]

    with t.span("outer") as outer:
        with t.span("inner") as inner:
            assert inner is outer  # one RPC = one span, terminals stack


def test_disabled_tracer_records_nothing():
    t = Tracer()
    t.enabled = False
    with t.span("ghost") as sp:
        assert sp is None
        with t.stage("device_compute"):
            pass
    assert t.snapshot()["traces"] == []
    # ...but EWMAs still update (auto-depth keeps working untraced)
    assert t.ewma("device_compute") is not None


def test_stage_ewma_and_dispatch_rtt():
    t = Tracer()
    assert t.dispatch_rtt() is None
    t.record_stage("device_dispatch", 0.0, 0.30, device=True)
    t.record_stage("device_compute", 0.0, 0.10, device=True)
    rtt = t.dispatch_rtt()
    assert rtt == pytest.approx(0.40)
    # EWMA converges toward repeated observations
    for _ in range(50):
        t.record_stage("device_compute", 0.0, 0.50, device=True)
    assert t.ewma("device_compute") == pytest.approx(0.50, rel=0.05)


def test_host_stages_do_not_feed_dispatch_rtt():
    """Host-path scans share the stage names (uniform traces) but must not
    shrink the auto-depth divisor: only device-marked records count."""
    t = Tracer()
    t.record_stage("device_compute", 0.0, 0.000005)  # µs host scan
    t.record_stage("device_dispatch", 0.0, 0.000001)
    assert t.dispatch_rtt() is None
    assert t.device_ewma("device_compute") is None
    t.record_stage("device_compute", 0.0, 0.02, device=True)
    assert t.device_ewma("device_compute") == pytest.approx(0.02)
    # the name-keyed EWMA (trace breakdowns) still sees both
    assert t.ewma("device_compute") is not None


# ------------------------------------------------------------- auto depth
def test_auto_depth_adapts_to_synthetic_slow_dispatch():
    """--sched-depth 0: depth follows the tracer's dispatch-RTT EWMA —
    synthetic slow dispatch (long RTT vs short compute) widens the
    pipeline, clamped to [AUTO_DEPTH_MIN, AUTO_DEPTH_MAX]."""
    TRACER.reset()
    sched = RequestScheduler(None, SchedConfig(depth=0))
    try:
        # no measurements yet: the safe default
        assert sched.current_depth() == AUTO_DEPTH_DEFAULT

        def measured_dispatch(dispatch_s, compute_s):
            def fn():
                # synthetic device timings recorded through the real
                # execution path (worker thread, ambient span handling)
                TRACER.record_stage("device_dispatch", 0.0, dispatch_s,
                                    device=True)
                TRACER.record_stage("device_compute", 0.0, compute_s,
                                    device=True)
                return True

            return fn

        # dispatch RTT ~6x compute -> depth ceil((0.5+0.1)/0.1) = 6
        for _ in range(40):
            assert sched.submit(measured_dispatch(0.5, 0.1))
        assert sched.current_depth() == 6

        # dispatch collapses (local chips): depth shrinks to the floor
        for _ in range(80):
            assert sched.submit(measured_dispatch(0.0001, 0.1))
        assert sched.current_depth() == AUTO_DEPTH_MIN

        # pathological RTT (wedged tunnel): clamped at the ceiling
        for _ in range(80):
            assert sched.submit(measured_dispatch(30.0, 0.1))
        assert sched.current_depth() == AUTO_DEPTH_MAX
    finally:
        sched.close()
        TRACER.reset()


def test_fixed_depth_ignores_tracer():
    TRACER.reset()
    try:
        TRACER.record_stage("device_dispatch", 0.0, 30.0, device=True)
        TRACER.record_stage("device_compute", 0.0, 0.1, device=True)
        sched = RequestScheduler(None, SchedConfig(depth=3))
        assert sched.current_depth() == 3
        sched.close()
    finally:
        TRACER.reset()


def test_cli_accepts_sched_depth_zero():
    from kubebrain_tpu.cli import validate_args

    args = build_parser().parse_args(["--sched-depth", "0"])
    validate_args(args)  # must not raise
    with pytest.raises(SystemExit):
        validate_args(build_parser().parse_args(["--sched-depth", "-1"]))


# ------------------------------------------------------- wire end-to-end
@pytest.fixture(scope="module")
def server():
    port = free_port()
    info_port = free_port()
    args = build_parser().parse_args([
        "--single-node", "--storage", "memkv", "--host", "127.0.0.1",
        "--client-port", str(port),
        "--peer-port", str(free_port()), "--info-port", str(info_port),
        "--trace-slow-ms", "10000",
    ])
    endpoint, backend, store = build_endpoint(args)
    endpoint.run()
    client = EtcdClient(f"127.0.0.1:{port}")
    for i in range(40):
        client.create(b"/registry/pods/default/pod-%04d" % i, b"x" * 64)
    yield client, port, info_port
    client.close()
    endpoint.close()
    backend.close()
    store.close()


def _http_json(info_port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{info_port}{path}", timeout=timeout
    ) as resp:
        return json.loads(resp.read())


def _http_text(info_port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{info_port}{path}", timeout=10
    ) as resp:
        return resp.read().decode()


def test_range_trace_stages_sum_to_latency(server):
    """Acceptance: a Range RPC through the real gRPC server yields a trace
    with >= 5 named stages whose durations sum to within 10% of the
    observed end-to-end latency, findable by the client's traceparent."""
    client, _port, info_port = server
    # warm the scheduler threads so queue_wait isn't dominated by startup
    for _ in range(3):
        client.range_(rpc_pb2.RangeRequest(
            key=b"/registry/pods/", range_end=b"/registry/pods0"))

    tp = make_traceparent()
    trace_id = parse_traceparent(tp)[0]
    client.range_(
        rpc_pb2.RangeRequest(key=b"/registry/pods/", range_end=b"/registry/pods0"),
        metadata=(("traceparent", tp),),
    )

    snap = _http_json(info_port, "/debug/traces")
    mine = [t for t in snap["traces"] if t["trace_id"] == trace_id]
    assert mine, f"trace {trace_id} not in /debug/traces"
    span = mine[0]
    assert span["name"] == "etcd.KV/Range"
    assert span["parent_id"] == parse_traceparent(tp)[1]
    stages = {s["stage"] for s in span["stages"]}
    assert len(stages) >= 5, span
    assert {"endpoint_recv", "queue_wait", "device_compute",
            "host_copy", "response_encode"} <= stages
    total = sum(s["duration_ms"] for s in span["stages"])
    assert total == pytest.approx(span["duration_ms"], rel=0.10), span


def test_stage_histogram_on_metrics(server):
    """queue-wait and device-compute appear in kb_rpc_stage_seconds on
    /metrics (alongside the sched gauges + the new depth/RTT gauges)."""
    client, _port, info_port = server
    client.range_(rpc_pb2.RangeRequest(
        key=b"/registry/pods/", range_end=b"/registry/pods0"))
    body = _http_text(info_port, "/metrics")
    assert 'kb_rpc_stage_seconds_bucket{' in body
    assert 'stage="queue_wait"' in body
    assert 'stage="device_compute"' in body
    assert "kb_sched_depth" in body
    assert "kb_sched_dispatch_rtt_seconds" in body


def test_watch_lag_and_backlog_metrics(server):
    """Watch-path lag instrumentation: commit->delivery histogram and the
    per-watcher backlog gauge surface on /metrics."""
    client, _port, info_port = server
    import queue as _q

    requests: _q.Queue = _q.Queue()
    req = rpc_pb2.WatchRequest()
    req.create_request.key = b"/registry/pods/"
    req.create_request.range_end = b"/registry/pods0"
    requests.put(req)
    responses = client.watch(iter(requests.get, None))
    first = next(iter(responses))
    assert first.created
    client.create(b"/registry/pods/default/watched-1", b"v")
    got = next(iter(responses))
    assert got.events
    body = _http_text(info_port, "/metrics")
    assert 'kb_watch_lag_seconds_bucket{' in body
    assert 'point="queue"' in body
    assert 'point="wire"' in body
    assert 'kb_watch_backlog{watcher=' in body
    requests.put(None)
    # watcher death unregisters its backlog gauge eagerly (no scrape
    # needed in between — unregister_gauge_fn, not just scrape-time GC)
    import time as _time

    deadline = _time.monotonic() + 5
    while _time.monotonic() < deadline:
        if 'kb_watch_backlog{watcher=' not in _http_text(info_port, "/metrics"):
            break
        _time.sleep(0.1)
    else:
        pytest.fail("dead watcher's backlog gauge still registered")


def test_slow_request_log_via_wire(server):
    """A request slower than --trace-slow-ms lands in the slow log; this
    server's threshold is 10s so the log stays empty."""
    _client, _port, info_port = server
    snap = _http_json(info_port, "/debug/traces")
    assert snap["slow_ms"] == 10000
    assert snap["slow"] == []
    assert snap["stage_ewma_seconds"].get("device_compute") is not None


def test_debug_profile_on_demand(server):
    """/debug/profile?seconds=N captures a jax.profiler device trace."""
    _client, _port, info_port = server
    # the first start_trace of a process initializes the XLA profiler
    # plugin (~15s in this container); later captures take ~the capture time
    out = _http_json(info_port, "/debug/profile?seconds=0.05", timeout=90)
    assert "trace_dir" in out, out
    assert out["seconds"] == pytest.approx(0.05)
    import os

    assert os.path.isdir(out["trace_dir"])
    # malformed query answers with a JSON error, not a 500
    out = _http_json(info_port, "/debug/profile?seconds=bogus")
    assert "error" in out


def test_traceparent_metadata_flows_from_client_lib(server):
    """EtcdCompatClient injects traceparent on every call — server spans
    come out parented without the caller doing anything."""
    _client, port, info_port = server
    from kubebrain_tpu.client import EtcdCompatClient

    c = EtcdCompatClient(f"127.0.0.1:{port}")
    try:
        kvs, _rev = c.list(b"/registry/pods/", b"/registry/pods0")
        assert len(kvs) >= 40
    finally:
        c.close()
    snap = _http_json(info_port, "/debug/traces")
    parented = [t for t in snap["traces"]
                if t["parent_id"] is not None and t["name"] == "etcd.KV/Range"]
    assert parented, "client-lib Range produced no parented server span"


def test_coalesced_follower_records_join_stage():
    """Coalesced followers carry a coalesce_join stage; the execution
    stages live on the leader's span."""
    import threading
    import time as _time

    TRACER.reset()
    t = Tracer()
    sched = RequestScheduler(None, SchedConfig(depth=1))
    release = threading.Event()
    results = []

    try:
        # blocker occupies the single slot; decoy is the dispatcher's
        # in-hand request; leader stays queued (pending) so the keyed
        # follower can join it
        blocker = sched.submit_async(lambda: release.wait(5.0), client="a")
        _time.sleep(0.05)
        decoy = sched.submit_async(lambda: "decoy", client="b")
        leader = sched.submit_async(lambda: "lead", client="c", key="K")
        _time.sleep(0.05)

        def follower():
            with t.span("follower"):
                results.append(sched.submit(lambda: "never-runs", client="d",
                                            key="K"))

        th = threading.Thread(target=follower)
        th.start()
        _time.sleep(0.05)
        release.set()
        th.join(timeout=5)
        assert results == ["lead"]
        for r in (blocker, decoy, leader):
            r.wait(5.0)
        follower_span = t.snapshot()["traces"][-1]
        assert follower_span["name"] == "follower"
        stages = {s["stage"] for s in follower_span["stages"]}
        assert "coalesce_join" in stages
    finally:
        sched.close()
        TRACER.reset()
