"""Watch pipeline tests: ring cache, hub fan-out, registration + live tail.

Reference: ring_test.go TestRing :26, testBackendWriteAndWatch :1177,
watch.go error cases :60-84, watcherhub.go slow-consumer drop :82-90.
"""

import queue

import pytest

from kubebrain_tpu.backend import (
    Backend,
    BackendConfig,
    Verb,
    WatchEvent,
    WatchExpiredError,
    wait_for_revision,
)
from kubebrain_tpu.backend.ring import Ring
from kubebrain_tpu.backend.watcherhub import WatcherHub
from kubebrain_tpu.storage import new_storage


# ----------------------------------------------------------------------- Ring
def test_ring_wraparound_and_find():
    r = Ring(4)
    for rev in range(1, 8):  # 7 events into cap-4 ring
        r.add(WatchEvent(revision=rev, key=b"k"))
    assert len(r) == 4
    assert r.oldest_revision() == 4
    assert r.latest_revision() == 7
    assert [e.revision for e in r.find_events(5)] == [5, 6, 7]
    assert [e.revision for e in r.find_events(1)] == [4, 5, 6, 7]
    assert r.find_events(8) == []


# ------------------------------------------------------------------------ Hub
def test_hub_fanout_filters():
    hub = WatcherHub()
    _, qa = hub.add_watcher(b"/a", b"/a\xff", 0)
    _, qb = hub.add_watcher(b"/b", b"/b\xff", 0)
    _, qlate = hub.add_watcher(b"", b"", 3)
    batch = [
        WatchEvent(revision=1, key=b"/a/1"),
        WatchEvent(revision=2, key=b"/b/1"),
        WatchEvent(revision=3, key=b"/a/2"),
    ]
    hub.stream(batch)
    assert [e.revision for e in qa.get_nowait()] == [1, 3]
    assert [e.revision for e in qb.get_nowait()] == [2]
    assert [e.revision for e in qlate.get_nowait()] == [3]


def test_hub_drops_slow_consumer(monkeypatch):
    import kubebrain_tpu.backend.watcherhub as wh

    monkeypatch.setattr(wh, "SUBSCRIBER_BUFFER", 2)
    hub = WatcherHub()
    wid, q = hub.add_watcher(b"", b"", 0)
    for rev in range(1, 5):  # buffer 2 → third push drops the watcher
        hub.stream([WatchEvent(revision=rev, key=b"/k")])
    assert hub.watcher_count() == 0
    # the drop protocol: the queue is FLAGGED dropped before anything is
    # evicted for the pill, and consumers check the flag before every
    # delivery — delivering a newer buffered batch after an older one was
    # evicted would be an invisible gap whose resume watermark skips the
    # evicted events (docs/replication.md delivered-order contract;
    # regression pinned in test_watch_robustness.py too)
    assert getattr(q, "kb_dropped", False)
    delivered = []
    while True:
        item = q.get_nowait()
        if item is None or getattr(q, "kb_dropped", False):
            break
        delivered.append(item)
    assert delivered == []


# ------------------------------------------------------------------- Backend
@pytest.fixture
def backend():
    store = new_storage("memkv")
    b = Backend(store, BackendConfig(event_ring_capacity=1024, watch_cache_capacity=64))
    yield b
    b.close()
    store.close()


def collect(q, n, timeout=5.0):
    out = []
    while len(out) < n:
        batch = q.get(timeout=timeout)
        assert batch is not None, "watch closed early"
        out.extend(batch)
    return out


def test_watch_live_tail(backend):
    wid, q = backend.watch(b"/registry/")
    r1 = backend.create(b"/registry/a", b"v1")
    r2 = backend.update(b"/registry/a", b"v2", r1)
    r3, _ = backend.delete(b"/registry/a")
    backend.create(b"/other/x", b"nope")  # filtered out
    events = collect(q, 3)
    assert [(e.revision, e.verb) for e in events] == [
        (r1, Verb.CREATE),
        (r2, Verb.PUT),
        (r3, Verb.DELETE),
    ]
    assert events[2].prev_revision == r2
    backend.unwatch(wid)


def test_watch_catchup_replay(backend):
    r1 = backend.create(b"/registry/a", b"v1")
    r2 = backend.create(b"/registry/b", b"v2")
    assert wait_for_revision(backend, r2)
    # register at r1: replay r1..r2 from cache, then live events follow
    wid, q = backend.watch(b"/registry/", revision=r1)
    events = collect(q, 2)
    assert [e.revision for e in events] == [r1, r2]
    r3 = backend.create(b"/registry/c", b"v3")
    events = collect(q, 1)
    assert events[0].revision == r3
    backend.unwatch(wid)


def test_watch_too_old_revision_expires(backend):
    # cache cap is 64: push enough events to evict revision 1
    for i in range(80):
        backend.create(b"/registry/k%03d" % i, b"v")
    assert wait_for_revision(backend, 80)
    with pytest.raises(WatchExpiredError):
        backend.watch(b"/registry/", revision=1)


def test_watch_failed_writes_invisible(backend):
    """Failed ops consume revisions but never reach watchers."""
    from kubebrain_tpu.backend import KeyExistsError

    wid, q = backend.watch(b"/")
    backend.create(b"/a", b"v")
    with pytest.raises(KeyExistsError):
        backend.create(b"/a", b"dup")
    backend.create(b"/b", b"v")
    events = collect(q, 2)
    assert [e.key for e in events] == [b"/a", b"/b"]
    assert [e.revision for e in events] == [1, 3]  # rev 2 was the failed dup
    with pytest.raises(queue.Empty):
        q.get_nowait()
    backend.unwatch(wid)


def test_watch_oldest_minus_one_expires_after_eviction(backend):
    """ADVICE r1 (medium): once the ring has evicted, oldest-1 may name a
    real dropped event — watching there must expire (reference watch.go
    'low' when revision < oldest), not silently skip the evicted event."""
    # cache cap is 64: fill past capacity so eviction has happened
    for i in range(80):
        backend.create(b"/registry/k%03d" % i, b"v")
    assert wait_for_revision(backend, 80)
    oldest = backend.watch_cache.oldest_revision()
    assert backend.watch_cache.has_evicted()
    with pytest.raises(WatchExpiredError):
        backend.watch(b"/registry/", revision=oldest - 1)
    # exactly oldest is still servable
    wid, q = backend.watch(b"/registry/", revision=oldest)
    backend.unwatch(wid)


def test_watch_oldest_minus_one_ok_before_eviction(backend):
    """On a never-full cache oldest-1 pre-dates all history (it is the
    revision the first cached event was written against — e.g. a leader
    seeded from the engine clock): replay from the first cached event is
    complete, so the -1 slack stays valid."""
    backend.set_current_revision(5)
    r1 = backend.create(b"/registry/a", b"v1")  # revision 6
    r2 = backend.create(b"/registry/b", b"v2")
    assert wait_for_revision(backend, r2)
    assert not backend.watch_cache.has_evicted()
    assert backend.watch_cache.oldest_revision() == r1 == 6
    wid, q = backend.watch(b"/registry/", revision=r1 - 1)
    events = collect(q, 2)
    assert [e.revision for e in events] == [r1, r2]
    backend.unwatch(wid)


def test_hub_dense_population_falls_back_correctly():
    """Hundreds of overlapping unbounded (from-key) watchers: the interval
    index aborts its build (dense) and the hub must still deliver exactly
    right via the fallback path."""
    from kubebrain_tpu.backend.common import Verb, WatchEvent
    from kubebrain_tpu.backend.watcherhub import WatcherHub, _RangeIndex

    hub = WatcherHub()
    qs = {}
    for i in range(200):
        # nested unbounded ranges: [/k-000.., inf), [/k-001.., inf), ...
        wid, q = hub.add_watcher(b"/k-%03d" % i, b"", 0)
        qs[wid] = (i, q)
    idx = _RangeIndex({w: (b"/k-%03d" % i, b"", 0) for w, (i, _) in qs.items()})
    assert idx.dense, "200 nested unbounded ranges must flag dense"

    ev = WatchEvent(revision=5, verb=Verb.CREATE, key=b"/k-100x", value=b"v",
                    valid=True)
    hub.stream([ev])
    got = sorted(i for i, q in qs.values() if not q.empty())
    # watchers 0..100 have start <= /k-100x; 101.. start above it
    assert got == list(range(101)), (len(got), got[:5], got[-5:])
