"""Watch robustness under server-side stream resets (docs/faults.md).

Proves the chaos-mode watch contract end to end through the real gRPC
front: a server-side watch drop (slow consumer or fault injection) makes
the resume-armed client WatchMux re-register from last-delivered
revision + 1 with NO lost and NO duplicated events; the slow-consumer
drop fires at the subscriber-queue bound and is scrape-visible.
"""

import queue
import threading
import time

import pytest

from test_etcd_server import free_port

from kubebrain_tpu.backend import Backend, BackendConfig
from kubebrain_tpu.client import EtcdCompatClient, WatchMux
from kubebrain_tpu.endpoint import Endpoint, EndpointConfig
from kubebrain_tpu.metrics import NoopMetrics, new_metrics
from kubebrain_tpu.server import Server
from kubebrain_tpu.server.service import SingleNodePeerService
from kubebrain_tpu.storage import new_storage


@pytest.fixture()
def served():
    store = new_storage("memkv")
    backend = Backend(store, BackendConfig(event_ring_capacity=8192))
    peers = SingleNodePeerService(backend)
    metrics = new_metrics("")  # real prometheus sink: drop counter visible
    backend.watcher_hub.set_metrics(metrics)
    server = Server(backend, peers, metrics)
    port = free_port()
    ep = Endpoint(server, metrics, EndpointConfig(
        host="127.0.0.1", client_port=port,
        peer_port=free_port(), info_port=free_port(),
    ))
    ep.run()
    yield f"127.0.0.1:{port}", backend, metrics
    ep.close()
    backend.close()
    store.close()


def _hub_wids(backend):
    return backend.watcher_hub.watcher_ids()


def _wait(cond, timeout=10.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_watchmux_resumes_after_server_side_reset(served):
    """Server-side stream reset mid-watch: the client resumes from
    last-delivered revision + 1 — every event delivered exactly once."""
    target, backend, _m = served
    c = EtcdCompatClient(target)
    mux = WatchMux(c, streams=2, resume=True, record_revisions=True)
    try:
        w = mux.add(b"/rw/", b"/rw0", start_revision=0, timeout=30.0)
        expected = []
        for i in range(5):
            expected.append(backend.create(b"/rw/k-%02d" % i, b"v%d" % i))
        _wait(lambda: w.events >= 5, what="first batch delivery")
        # server-side reset: drop the hub watcher (the same path a slow-
        # consumer drop and the fault plane's watch_reset injection take)
        wids = _hub_wids(backend)
        assert len(wids) == 1
        backend.watcher_hub.delete_watcher(wids[0])
        # events written WHILE the client re-registers: the watch cache
        # replays them on resume — none may be lost
        for i in range(5, 12):
            expected.append(backend.create(b"/rw/k-%02d" % i, b"v%d" % i))
        _wait(lambda: w.events >= 12, what="post-resume delivery")
        _wait(lambda: w.resumes >= 1, what="resume accounting")
        assert not w.cancelled
        # exactly once, in revision order: no loss, no duplicates
        assert w.revisions == expected
        # the server sees a live watcher again
        _wait(lambda: len(_hub_wids(backend)) == 1, what="re-registration")
    finally:
        mux.close()
        c.close()


def test_watchmux_survives_repeated_resets_no_loss_no_dup(served):
    """Chaos cadence: resets fired repeatedly while a writer streams —
    the delivered revision sequence must be the exact commit sequence."""
    target, backend, _m = served
    c = EtcdCompatClient(target)
    mux = WatchMux(c, streams=1, resume=True, record_revisions=True)
    try:
        w = mux.add(b"/rr/", b"/rr0", start_revision=0, timeout=30.0)
        expected = []
        stop = threading.Event()

        def nemesis():
            while not stop.is_set():
                for wid in _hub_wids(backend):
                    backend.watcher_hub.delete_watcher(wid)
                time.sleep(0.05)

        t = threading.Thread(target=nemesis, daemon=True)
        t.start()
        for i in range(60):
            expected.append(backend.create(b"/rr/k-%03d" % i, b"v"))
            time.sleep(0.005)
        stop.set()
        t.join(timeout=5)
        _wait(lambda: w.events >= 60, timeout=20.0,
              what="all events after repeated resets")
        assert w.revisions == expected, (
            f"lost={set(expected) - set(w.revisions)} "
            f"dup={[r for r in w.revisions if w.revisions.count(r) > 1]}")
        assert w.resumes >= 1 and not w.cancelled
    finally:
        mux.close()
        c.close()


def test_resume_not_armed_keeps_terminal_cancel(served):
    """Without resume (the pre-chaos default) a server-side drop stays a
    terminal cancel — the legacy contract is unchanged."""
    target, backend, _m = served
    c = EtcdCompatClient(target)
    mux = WatchMux(c, streams=1, resume=False)
    try:
        w = mux.add(b"/nc/", b"/nc0", start_revision=0, timeout=30.0)
        backend.create(b"/nc/k", b"v")
        _wait(lambda: w.events >= 1, what="delivery")
        for wid in _hub_wids(backend):
            backend.watcher_hub.delete_watcher(wid)
        _wait(lambda: w.cancelled, what="terminal cancel")
        assert w.resumes == 0
    finally:
        mux.close()
        c.close()


def test_slow_consumer_drop_fires_at_backlog_bound():
    """The documented backlog bound: a consumer that stops draining is
    dropped once its subscriber queue fills, the poison pill ends the
    stream, and the drop is visible on /metrics (kb_watch_dropped_total)
    alongside the kb_watch_backlog gauge."""
    store = new_storage("memkv")
    backend = Backend(store, BackendConfig(event_ring_capacity=8192))
    metrics = new_metrics("")
    backend.watcher_hub.set_metrics(metrics)
    try:
        bound = 4
        wid, q = backend.watch_range(
            b"/sc/", b"/sc0",
            queue_factory=lambda _maxsize: queue.Queue(maxsize=bound))
        # backlog gauge reflects the (undrained) queue depth
        for i in range(bound):
            backend.create(b"/sc/k-%02d" % i, b"v")
        _ctype, body = metrics.http_handler()()
        text = body.decode()
        assert f'kb_watch_backlog{{watcher="{wid}"}} {float(bound)}' in text
        # one more batch past the bound: the hub drops the watcher
        backend.create(b"/sc/k-xx", b"v")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline \
                and backend.watcher_hub.watcher_count() > 0:
            time.sleep(0.02)
        assert backend.watcher_hub.watcher_count() == 0
        # the stream ends with the poison pill (after the buffered batches)
        seen_pill = False
        while True:
            try:
                item = q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                seen_pill = True
        assert seen_pill, "dropped watcher never got the poison pill"
        _ctype, body = metrics.http_handler()()
        assert "kb_watch_dropped_total 1.0" in body.decode()
    finally:
        backend.close()
        store.close()


def test_fault_plane_watch_reset_drops_live_watchers():
    """The plane's watch_reset injection drops seeded-random live hub
    watchers — the server half of the resume contract."""
    from kubebrain_tpu import faults

    store = new_storage("memkv")
    backend = Backend(store, BackendConfig())
    try:
        plane = faults.FaultPlane(faults.generate("watch", 1, 5.0))
        plane.bind_hub(backend.watcher_hub)
        wids = [backend.watch_range(b"/fp/", b"/fp0")[0] for _ in range(6)]
        assert backend.watcher_hub.watcher_count() == 6
        assert plane._reset_watchers(2) == 2
        assert backend.watcher_hub.watcher_count() == 4
        assert plane._reset_watchers(100) == 4  # clamped to live set
        assert backend.watcher_hub.watcher_count() == 0
        assert wids  # ids were real
    finally:
        backend.close()
        store.close()


# ------------------------------------------------- delivered-order holes
# Two server-side paths used to open an INVISIBLE gap in a
# delivered-in-order watch stream — fatal once a later response (event
# batch or replica progress mark) vouches for the skipped revisions and a
# resume watermark carries the loss forward (docs/replication.md):
#   1. _WatchSession._send dropping ONE response when the per-stream out
#      queue stayed full, while later responses kept flowing;
#   2. WatcherHub.delete_watcher evicting only the HEAD of a full
#      subscriber queue to fit the poison pill, delivering the newer
#      batches after the gap.
# Both must instead END the stream at the last delivered response.

def test_session_send_overflow_poisons_stream_instead_of_gapping():
    from kubebrain_tpu.server.etcd.watch import _WatchSession

    store = new_storage("memkv")
    backend = Backend(store, BackendConfig())
    try:
        out: queue.Queue = queue.Queue(maxsize=2)
        session = _WatchSession(backend, out, context=None)
        out.put("r1")
        out.put("r2")  # full: the next _send cannot deliver in order
        session._send("r3-would-gap")
        # the session must be POISONED (the stream writer checks the flag
        # before every yield, so the wire sequence stays a strict prefix
        # of the enqueued order) — never a silent skip of one response
        assert session.poisoned
        with session._lock:
            assert session._closed
        # the dropped response never entered the queue
        assert "r3-would-gap" not in list(out.queue)
    finally:
        backend.close()
        store.close()


def test_delete_watcher_flags_before_pill():
    from kubebrain_tpu.backend.common import WatchEvent
    from kubebrain_tpu.backend.watcherhub import WatcherHub

    hub = WatcherHub()
    wid, q = hub.add_watcher(
        b"", b"", 0, queue_factory=lambda _ms: queue.Queue(maxsize=3))
    for rev in (1, 2, 3):
        hub.stream([WatchEvent(revision=rev, key=b"/k%d" % rev)])
    assert q.full()
    hub.delete_watcher(wid)
    # nothing may be delivered past the drop point: delete_watcher sets
    # kb_dropped BEFORE evicting for the pill, and the pump checks it
    # before every delivery — a consumer seeing batch 2 or 3 after batch
    # 1 was evicted would resume past rev 1 (the invisible-gap shape)
    assert getattr(q, "kb_dropped", False)
    delivered = []
    while True:
        item = q.get_nowait()
        if item is None or getattr(q, "kb_dropped", False):
            break
        delivered.append(item)
    assert delivered == []
