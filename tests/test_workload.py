"""Workload replay harness tests: generator determinism (the seed ⇒
byte-identical-trace contract), SLO report schema + evaluation, the
client-side mux/bulk helpers, and a small-N end-to-end replay through the
real gRPC front whose report must reconcile with the server's /metrics.
"""

import json
import os
import time

import pytest

from kubebrain_tpu.workload import generator, slo
from kubebrain_tpu.workload.clock import EventWheel
from kubebrain_tpu.workload.spec import SLOBounds, WorkloadSpec

from test_etcd_server import free_port


# --------------------------------------------------------------- generator
def test_trace_determinism_byte_identical():
    """Same seed + N => byte-identical generated op trace, twice."""
    spec = WorkloadSpec.for_smoke(16, seed=7)
    a = generator.generate(spec)
    b = generator.generate(spec)
    assert a.trace_bytes() == b.trace_bytes()
    assert a.sha256() == b.sha256()
    # and a fresh-process-equivalent check: the schedule is derived only
    # from the spec, so a third run after unrelated RNG use must agree
    import random
    random.random()
    assert generator.generate(spec).sha256() == a.sha256()


def test_trace_seed_and_shape_sensitivity():
    base = WorkloadSpec.for_smoke(16, seed=7)
    assert generator.generate(base.with_(seed=8)).sha256() != \
        generator.generate(base).sha256()
    assert generator.generate(base.with_(nodes=17)).sha256() != \
        generator.generate(base).sha256()


def test_trace_covers_every_traffic_shape():
    spec = WorkloadSpec.for_smoke(12, seed=3)
    sched = generator.generate(spec)
    counts = sched.counts()
    for kind in generator.ALL_KINDS:
        assert counts.get(kind, 0) > 0, f"no {kind} ops generated"
    assert counts[generator.LEASE_GRANT] == spec.nodes
    assert counts[generator.CTRL_START] == spec.nodes
    assert counts[generator.LEASE_KEEPALIVE] >= spec.nodes
    assert counts[generator.PRELOAD_CREATE] == spec.nodes * spec.pods_per_node
    # replay is time-ordered with a stable tie-break
    replay = sched.replay
    assert all(a.t_ms <= b.t_ms for a, b in zip(replay, replay[1:]))
    assert all(a.seq < b.seq for a, b in zip(replay, replay[1:]))
    # key shapes: hierarchical /registry/... paths (FOCUS distribution)
    for op in sched.ops:
        if op.kind.startswith("POD") or op.kind == generator.PRELOAD_CREATE:
            assert op.key.startswith(generator.PODS_PREFIX)
            assert op.key.count(b"/") == 4  # /registry/pods/<ns>/<name>
        if op.kind == generator.LEASE_GRANT:
            assert op.key.startswith(generator.LEASE_PREFIX)


def test_generator_never_updates_deleted_pods():
    sched = generator.generate(WorkloadSpec.for_smoke(10, seed=11))
    dead: set = set()
    for op in sched.replay:
        if op.kind == generator.POD_DELETE:
            dead.add(op.key)
        elif op.kind in (generator.POD_UPDATE, generator.POD_CREATE):
            assert op.key not in dead, f"{op.kind} on deleted key {op.key!r}"


def test_event_wheel_deterministic_tiebreak():
    w = EventWheel()
    w.push(5, "b", 1)
    w.push(5, "a", 2)
    w.push(1, "c", 3)
    assert [w.pop() for _ in range(3)] == [
        (1, "c", 3), (5, "b", 1), (5, "a", 2)]
    with pytest.raises(ValueError):
        w.push(-1, "x")


def test_spec_validation_rejects_expiring_keepalives():
    with pytest.raises(ValueError):
        WorkloadSpec(keepalive_interval_s=60.0, time_scale=1.0,
                     lease_ttl_s=5).validate()


# ------------------------------------------------------------- SLO helpers
_PROM = """\
# HELP rpc_server_count_total rpc_server_count_total
rpc_server_count_total{method="/etcdserverpb.KV/Txn",success="true"} 40
rpc_server_count_total{method="/etcdserverpb.KV/Txn",success="false"} 2
rpc_server_count_total{method="/etcdserverpb.KV/Range",success="true"} 17
kb_lease_granted_total 8
kb_watch_backlog{watcher="3"} 0
kb_watch_backlog{watcher="9"} 2
kb_watch_lag_seconds_bucket{point="wire",le="0.01"} 90
kb_watch_lag_seconds_bucket{point="wire",le="0.1"} 99
kb_watch_lag_seconds_bucket{point="wire",le="+Inf"} 100
kb_watch_lag_seconds_count{point="wire"} 100
kb_watch_lag_seconds_sum{point="wire"} 0.5
"""


def test_prom_parse_and_lookups():
    snap = slo.parse_prom(_PROM)
    assert slo.series_sum(snap, "rpc_server_count",
                          method="/etcdserverpb.KV/Txn") == 42
    assert slo.series_sum(snap, "kb_lease_granted_total") == 8
    assert slo.series_count(snap, "kb_watch_backlog") == 2
    count, total = slo.hist_count_sum(snap, "kb_watch_lag_seconds", point="wire")
    assert (count, total) == (100, 0.5)
    # p50 inside the first bucket, p99 interpolated inside the second
    p50 = slo.hist_quantile(snap, "kb_watch_lag_seconds", 0.5, point="wire")
    p99 = slo.hist_quantile(snap, "kb_watch_lag_seconds", 0.99, point="wire")
    assert 0.0 < p50 <= 0.01
    assert 0.01 < p99 <= 0.1
    # +Inf landings report the top finite bound, not a fabricated tail
    assert slo.hist_quantile(snap, "kb_watch_lag_seconds", 1.0,
                             point="wire") == 0.1
    assert slo.hist_quantile(snap, "nope", 0.5) is None


def _minimal_report(**overrides) -> dict:
    lane = {"count": 10, "ok": 10, "shed": 0, "errors": 0,
            "p50_ms": 1.0, "p99_ms": 2.0}
    report = {
        "schema": slo.SCHEMA_ID,
        "spec": {"nodes": 4, "seed": 0, "duration_s": 5.0, "time_scale": 5.0},
        "platform": {"platform": "cpu", "device": "test"},
        "trace": {"sha256": "x", "ops": 40, "preload_ops": 8, "replay_ops": 32},
        "replay": {"wall_s": 1.0, "ops_per_sec": 32.0,
                   "max_dispatch_lag_s": 0.0, "drained": True},
        "lanes": {"system": dict(lane), "normal": dict(lane),
                  "background": dict(lane), "write": dict(lane)},
        "op_kinds": {"COMPACT": {"count": 1, "ok": 1}},
        "watch": {"watchers": 4, "events": 12, "cancelled": 0,
                  "lag_wire_p99_s": 0.01, "lag_queue_p99_s": 0.01},
        "leases": {"granted": 4, "keepalives_sent": 8, "keepalives_acked": 8,
                   "expired_acks": 0, "metrics": {"expired_delta": 0}},
        "sched": {"batched_launches": 0, "batched_requests": 0,
                  "write_batched_groups": 0, "write_batched_ops": 0,
                  "shed_total": 0, "coalesced_total": 0},
        "compact": {"completed": 1, "skipped": 0, "phases": {},
                    "victims": {}, "errors": 0, "retries": 0,
                    "escalations": 0, "full_rebuilds": 0},
        "reconcile": {"ok": True, "checks": {}},
        "slo": {"pass": True, "violations": [], "bounds": {}},
        "errors": [],
        "faults": {"armed": False},
    }
    report.update(overrides)
    return report


def test_report_schema_validation():
    slo.validate_report(_minimal_report())  # must not raise
    with pytest.raises(ValueError, match="watch"):
        slo.validate_report(_minimal_report(watch={"watchers": 1}))
    bad = _minimal_report()
    del bad["reconcile"]
    with pytest.raises(ValueError, match="reconcile"):
        slo.validate_report(bad)
    with pytest.raises(ValueError, match="schema"):
        slo.validate_report(_minimal_report(schema="nope/v0"))
    broken_lane = _minimal_report()
    del broken_lane["lanes"]["write"]["p99_ms"]
    with pytest.raises(ValueError, match="write"):
        slo.validate_report(broken_lane)


def test_slo_evaluation_bounds():
    bounds = SLOBounds()
    ok, v = slo.evaluate(_minimal_report(), bounds)
    assert ok and v == []
    # lease expiries violate
    r = _minimal_report()
    r["leases"]["metrics"]["expired_delta"] = 3
    ok, v = slo.evaluate(r, bounds)
    assert not ok and any("expir" in x for x in v)
    # reconciliation failure violates
    r = _minimal_report(reconcile={"ok": False, "checks": {
        "txn_rpcs": {"client": 5, "server": 4, "ok": False}}})
    ok, v = slo.evaluate(r, bounds)
    assert not ok and any("txn_rpcs" in x for x in v)
    # lane p99 over bound violates
    r = _minimal_report()
    r["lanes"]["system"]["p99_ms"] = bounds.system_p99_ms + 1
    ok, v = slo.evaluate(r, bounds)
    assert not ok and any("lane system" in x for x in v)
    # missing compaction violates — and skipped/errored attempts don't
    # count as completed ones
    r = _minimal_report(op_kinds={})
    ok, v = slo.evaluate(r, bounds)
    assert not ok and any("compaction" in x for x in v)
    r = _minimal_report(op_kinds={"COMPACT": {"count": 3, "ok": 0}})
    ok, v = slo.evaluate(r, bounds)
    assert not ok and any("compaction" in x for x in v)
    # a drain timeout is named explicitly (reconcile races in-flight ops)
    r = _minimal_report()
    r["replay"]["drained"] = False
    ok, v = slo.evaluate(r, bounds)
    assert not ok and any("drain" in x for x in v)


def test_next_report_path(tmp_path):
    assert slo.next_report_path(str(tmp_path)).endswith("WORKLOAD_r01.json")
    (tmp_path / "WORKLOAD_r07.json").write_text("{}")
    assert slo.next_report_path(str(tmp_path)).endswith("WORKLOAD_r08.json")


# -------------------------------------------------- client-side mux helpers
@pytest.fixture(scope="module")
def served():
    from kubebrain_tpu.backend import Backend, BackendConfig
    from kubebrain_tpu.endpoint import Endpoint, EndpointConfig
    from kubebrain_tpu.metrics import NoopMetrics
    from kubebrain_tpu.server import Server
    from kubebrain_tpu.server.service import SingleNodePeerService
    from kubebrain_tpu.storage import new_storage

    store = new_storage("memkv")
    backend = Backend(store, BackendConfig(event_ring_capacity=8192))
    peers = SingleNodePeerService(backend)
    server = Server(backend, peers, NoopMetrics())
    port = free_port()
    ep = Endpoint(server, NoopMetrics(), EndpointConfig(
        host="127.0.0.1", client_port=port,
        peer_port=free_port(), info_port=free_port(),
    ))
    ep.run()
    yield f"127.0.0.1:{port}", backend
    ep.close()
    backend.close()
    store.close()


def test_create_bulk_pipelined(served):
    from kubebrain_tpu.client import EtcdCompatClient

    target, backend = served
    c = EtcdCompatClient(target)
    try:
        items = [(b"/registry/bulk/k-%04d" % i, b"v%d" % i) for i in range(300)]
        results = c.create_bulk(items, window=32)
        assert len(results) == 300
        assert all(ok for ok, _rev in results)
        # results align with input order: each key's reported revision is
        # the server's mod revision for THAT key (commits interleave across
        # the window, so revisions are not monotone with input order)
        revs = [rev for _ok, rev in results]
        for (key, _v), rev in zip(items[:10], revs[:10]):
            got = c.get(key)
            assert got is not None and got.mod_revision == rev
        # duplicate keys conflict, reporting the existing revision
        dup = c.create_bulk(items[:5], window=4)
        assert [ok for ok, _ in dup] == [False] * 5
        assert [rev for _, rev in dup] == revs[:5]
        kvs, _rev = c.list_unpaged(b"/registry/bulk/", b"/registry/bulk0")
        assert len(kvs) == 300
    finally:
        c.close()


def test_watch_mux_many_watches_few_streams(served):
    from kubebrain_tpu.client import EtcdCompatClient, WatchMux

    target, _backend = served
    c = EtcdCompatClient(target)
    mux = WatchMux(c, streams=2)
    try:
        watches = []
        for ns in range(6):
            prefix = b"/registry/muxwatch/ns-%d/" % ns
            w = mux.add(prefix, prefix + b"\xff", shard=ns)
            assert w.watch_id >= 0
            watches.append(w)
        assert len({id(s) for s in mux._streams}) == 2
        for ns in range(6):
            ok, _ = c.create(b"/registry/muxwatch/ns-%d/pod-a" % ns, b"x")
            assert ok
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and mux.total_events() < 6:
            time.sleep(0.05)
        assert mux.total_events() == 6
        assert all(w.events == 1 for w in watches)
        assert mux.cancelled_count() == 0
    finally:
        mux.close()
        c.close()


def test_lease_mux_grant_and_keepalive(served):
    from kubebrain_tpu.client import EtcdCompatClient, LeaseMux

    target, _backend = served
    c = EtcdCompatClient(target)
    mux = LeaseMux(c, streams=2)
    try:
        ids = mux.grant_bulk(5, ttl=30, window=2)
        assert len(ids) == len(set(ids)) == 5
        acks = []
        for i, lid in enumerate(ids):
            assert mux.keepalive_async(
                lid, shard=i, on_ack=lambda dt, ttl: acks.append(ttl))
        assert mux.flush(10.0)
        assert mux.sent == mux.acked == 5
        assert mux.expired_acks == 0
        assert len(acks) == 5 and all(t > 0 for t in acks)
        # an unknown lease acks TTL=0 (expired encoding), counted as such
        assert mux.keepalive_async(1234567890123, shard=0)
        assert mux.flush(10.0)
        assert mux.expired_acks == 1
    finally:
        mux.close()
        c.close()  # granted leases just expire server-side


# --------------------------------------------------------- end-to-end smoke
def test_small_n_replay_smoke(tmp_path):
    """The CI gate: a small-N replay through a real spawned server must
    drive all four subsystems, reconcile against /metrics, and emit a
    schema-valid passing SLO report."""
    from kubebrain_tpu.workload.runner import run_workload

    spec = WorkloadSpec.for_smoke(8, seed=1)
    out = str(tmp_path / "WORKLOAD_smoke.json")
    report = run_workload(spec, out_path=out)

    slo.validate_report(report)
    assert report["slo"]["pass"], report["slo"]["violations"]
    assert report["reconcile"]["ok"], report["reconcile"]["checks"]

    # op counts reconcile with server-side /metrics counters
    checks = report["reconcile"]["checks"]
    for name in ("txn_rpcs", "range_rpcs", "compact_rpcs",
                 "lease_grant_rpcs", "lease_keepalives", "watchers"):
        assert checks[name]["ok"], (name, checks[name])
        assert checks[name]["client"] > 0, (name, checks[name])

    # all four subsystems saw traffic in ONE run
    assert report["watch"]["watchers"] == spec.nodes          # watch hub
    assert report["watch"]["events"] > 0
    assert report["leases"]["granted"] == spec.nodes          # lease registry
    assert report["leases"]["keepalives_acked"] >= spec.nodes
    assert report["leases"]["metrics"]["expired_delta"] == 0
    assert report["op_kinds"]["COMPACT"]["ok"] >= 1           # compaction
    for lane in ("system", "normal", "background", "write"):  # scheduler lanes
        assert report["lanes"][lane]["count"] > 0, lane
    assert report["watch"]["lag_wire_p99_s"] is not None

    # the replayed trace is the generated trace
    assert report["trace"]["sha256"] == \
        generator.generate(spec).sha256()
    assert report["trace"]["determinism_checked"]

    # report landed on disk, valid JSON, same content
    with open(out, encoding="utf-8") as f:
        on_disk = json.load(f)
    slo.validate_report(on_disk)
    assert on_disk["trace"]["sha256"] == report["trace"]["sha256"]
    assert os.path.getsize(out) > 500


def test_churn_heavy_scenario_forms_write_groups():
    """The churn_heavy preset (docs/writes.md): pod churn + keepalive
    storm through the real gRPC front must actually form write commit
    groups on the server — kb_sched_write_batch_size COUNT moves, the
    reconcile section carries the mandatory write_groups_formed check,
    and the run passes its declared SLOs."""
    from kubebrain_tpu.workload.runner import run_workload

    spec = WorkloadSpec.for_churn_heavy(
        60, seed=1, duration_s=6.0, time_scale=3.0,
        compact_interval_s=2.5)  # >= 1 compaction inside the short window
    assert spec.bounds.min_write_batched_ops > 0
    report = run_workload(spec, write_report=False)

    slo.validate_report(report)
    assert report["slo"]["pass"], report["slo"]["violations"]
    sched = report["sched"]
    assert sched["write_batched_groups"] > 0
    assert sched["write_batched_ops"] >= spec.bounds.min_write_batched_ops
    # ops-per-group is a real mean over >= 2-op groups
    assert sched["write_batched_ops"] >= 2 * sched["write_batched_groups"]
    check = report["reconcile"]["checks"]["write_groups_formed"]
    assert check["ok"], check
    # the write skew actually skewed: more write ops than list/relist reads
    writes = report["lanes"]["write"]["count"]
    reads = (report["lanes"]["normal"]["count"]
             + report["lanes"]["background"]["count"])
    assert writes > reads, (writes, reads)


def test_churn_heavy_bound_fails_without_group_formation():
    """min_write_batched_ops is a REAL bound: a report with no group
    formation must fail the churn_heavy SLO evaluation."""
    from kubebrain_tpu.workload.spec import SLOBounds

    report = _minimal_report()
    passed, violations = slo.evaluate(
        report, SLOBounds(min_compactions=0, min_write_batched_ops=2))
    assert not passed
    assert any("group commit never formed" in v for v in violations)
