"""Write-path group commit tests (docs/writes.md).

Grouped commits must be byte-identical to back-to-back sequential
commits BY CONSTRUCTION — same revisions, same per-op results, same
errors, same watch events in the same order. These tests pin that
construction:

- randomized grouped-vs-sequential differential (incl. concurrent
  readers on the grouped backend);
- per-op conflict demux inside one group (CAS mismatch / KeyExists /
  KeyNotFound fail ONLY their own op, and consume their dealt revision
  exactly like the sequential paths);
- same-key-in-group ordering (a group member validates against the
  state as mutated by earlier members of the SAME group);
- watch events strictly revision-ordered across group boundaries;
- scheduler group formation (plugged-slot deterministic) equals the
  sequential oracle byte for byte and per-client FIFO survives;
- the TPU mirror's incremental stored-domain delta merge equals the
  full host rebuild byte for byte (jnp + pallas-interpret, one and two
  partitions per device) with merge accounting proving no full rebuild
  ran in steady state;
- engines without ``write_batch`` fall back per-op with identical
  results.
"""

import threading
import time

import numpy as np
import pytest

from kubebrain_tpu.backend import (
    Backend,
    BackendConfig,
    CASRevisionMismatchError,
    FutureRevisionError,
    KeyExistsError,
)
from kubebrain_tpu.parallel.mesh import make_mesh
from kubebrain_tpu.storage import new_storage
from kubebrain_tpu.storage.errors import KeyNotFoundError
from kubebrain_tpu.storage.tpu.engine import TpuKvStorage


def mk_backend(store=None, ring=16384):
    store = store or new_storage("memkv")
    return store, Backend(store, BackendConfig(event_ring_capacity=ring,
                                               watch_cache_capacity=4096))


def fp_op_result(r):
    """One comparable fingerprint per op result (success value or error)."""
    if isinstance(r, BaseException):
        return (type(r).__name__, str(r))
    if isinstance(r, tuple):  # delete: (rev, KeyValue)
        rev, kv = r
        return ("del", rev, kv.key, kv.value, kv.revision)
    return ("rev", r)


def fp_state(b: Backend):
    res = b.list_(b"/registry/", b"/registry0", 0, 0)
    return ([(kv.key, kv.value, kv.revision) for kv in res.kvs],
            res.revision, b.current_revision())


def gen_ops(rng, n, keyspace=24):
    """A random create/update/delete stream with plausible conflicts:
    updates CAS against a tracked (sometimes stale) revision, creates
    sometimes target live keys, deletes sometimes guard a wrong rev."""
    live: dict[bytes, int] = {}
    next_rev = [0]
    ops = []
    for step in range(n):
        k = b"/registry/pods/ns-%d/p-%02d" % (step % 3, rng.randint(keyspace))
        roll = rng.rand()
        if k not in live or roll < 0.3:
            ops.append(("create", k, b"c%04d" % step, None, 0))
            kind = "create"
        elif roll < 0.75:
            exp = live[k] if rng.rand() < 0.8 else max(1, live[k] - 1)
            ops.append(("update", k, b"u%04d" % step, exp, None, 0))
            kind = "update" if exp == live[k] else "update-stale"
        else:
            droll = rng.rand()
            if droll < 0.5:
                exp = 0
            elif droll < 0.8:
                exp = live[k]
            else:
                exp = live[k] + 7  # stale guard: this delete MUST fail
            ops.append(("delete", k, exp))
            kind = "delete" if exp in (0, live[k]) else "delete-stale"
        # track what a successful sequential application would do (close
        # enough for conflict-shaping; exactness comes from the oracle)
        next_rev[0] += 1
        if kind == "create" and k not in live:
            live[k] = next_rev[0]
        elif kind == "update":
            live[k] = next_rev[0]
        elif kind == "delete" and (exp in (0, live.get(k))):
            live.pop(k, None)
    return ops


def test_grouped_vs_sequential_randomized_byte_identity():
    """Random op stream chopped into random-size groups on backend A vs
    the same stream sequentially on backend B: per-op results AND final
    state identical, while reader threads hammer A mid-commit."""
    rng = np.random.RandomState(7)
    ops = gen_ops(rng, 240)
    _, grouped = mk_backend()
    _, seq = mk_backend()

    stop = threading.Event()
    reader_errs: list = []

    def reader():
        while not stop.is_set():
            try:
                res = grouped.list_(b"/registry/", b"/registry0", 0, 0)
                keys = [kv.key for kv in res.kvs]
                assert keys == sorted(keys) and len(set(keys)) == len(keys)
            except Exception as e:  # pragma: no cover - surfaced below
                reader_errs.append(e)
                return

    readers = [threading.Thread(target=reader) for _ in range(3)]
    for t in readers:
        t.start()

    got, want = [], []
    i = 0
    try:
        while i < len(ops):
            g = int(rng.randint(1, 9))
            group = ops[i:i + g]
            got.extend(fp_op_result(r) for r in grouped.write_batch(group))
            for op in group:
                try:
                    want.append(fp_op_result(seq._apply_single(op)))
                except BaseException as e:
                    want.append(fp_op_result(e))
            i += g
    finally:
        stop.set()
        for t in readers:
            t.join(10)

    assert not reader_errs, reader_errs[0]
    assert got == want
    assert fp_state(grouped) == fp_state(seq)
    grouped.close()
    seq.close()


def test_per_op_conflict_demux_in_one_group():
    """One group holding every conflict kind: each failure is demuxed to
    its own op, later ops still land, and every dealt revision is
    consumed (etcd-style gaps) exactly like the sequential paths."""
    _, b = mk_backend()
    r1 = b.create(b"/registry/a", b"v1")       # rev 1
    r2 = b.update(b"/registry/a", b"v2", r1)   # rev 2: r1 is now truly stale
    base = b.current_revision()

    res = b.write_batch([
        ("create", b"/registry/ok", b"x", None, 0),        # ok      -> base+1
        ("create", b"/registry/a", b"dup", None, 0),       # exists  (base+2 consumed)
        ("update", b"/registry/a", b"y", r1, None, 0),     # CAS mism(base+3 consumed)
        ("delete", b"/registry/missing", 0),               # not found
        ("update", b"/registry/a", b"z", r2, None, 0),     # ok      -> base+5
        ("delete", b"/registry/ok", 0),                    # ok      -> base+6
    ])
    assert res[0] == base + 1
    assert isinstance(res[1], KeyExistsError) and res[1].revision == r2
    assert isinstance(res[2], CASRevisionMismatchError)
    assert res[2].revision == r2 and res[2].value == b"v2"
    assert isinstance(res[3], KeyNotFoundError)
    assert res[4] == base + 5
    rev, kv = res[5]
    assert rev == base + 6 and kv.value == b"x" and kv.revision == base + 1
    # failed ops consumed their revisions: the clock advanced by the
    # full group size and the sequencer is fully drained
    assert b.current_revision() == base + 6
    got = b.list_(b"/registry/", b"/registry0", 0, 0)
    assert [(kv.key, kv.value) for kv in got.kvs] == [(b"/registry/a", b"z")]
    b.close()


def test_failed_delete_consumes_revision_grouped_and_sequential():
    """A stale-guard delete consumes its dealt revision on BOTH paths —
    grouped (block dealt up front) and sequential (memkv's mvcc_delete
    routes deletes through _delete_fast) — so the revision a later op
    lands on cannot depend on whether it happened to ride a group.
    Regression: memkv's slow-path delete used to pre-validate without
    dealing, so sequential skipped the revision a group consumed."""
    _, grouped = mk_backend()
    _, seq = mk_backend()
    for b in (grouped, seq):
        b.create(b"/registry/a", b"v1")  # rev 1

    ops = [("delete", b"/registry/a", 999),          # stale guard: fails
           ("create", b"/registry/b", b"v2", None, 0)]
    got = [fp_op_result(r) for r in grouped.write_batch(ops)]
    want = []
    for op in ops:
        try:
            want.append(fp_op_result(seq._apply_single(op)))
        except BaseException as e:
            want.append(fp_op_result(e))

    assert got == want
    assert got[0][0] == "CASRevisionMismatchError"
    # the failed delete consumed rev 2 on both: /registry/b landed on 3
    assert got[1] == ("rev", 3)
    assert fp_state(grouped) == fp_state(seq)
    assert seq.current_revision() == 3
    grouped.close()
    seq.close()


def test_same_key_in_group_ordering():
    """Same-key ops inside ONE group behave as back-to-back sequential
    commits: each validates against the state as mutated by earlier
    members (create -> update-over-that-create -> delete-over-that)."""
    _, b = mk_backend()
    base = b.current_revision()
    res = b.write_batch([
        ("create", b"/registry/k", b"v0", None, 0),
        ("update", b"/registry/k", b"v1", base + 1, None, 0),
        ("update", b"/registry/k", b"v2", base + 2, None, 0),
        ("update", b"/registry/k", b"stale", base + 1, None, 0),  # loses
        ("delete", b"/registry/k", base + 3),
        ("create", b"/registry/k", b"reborn", None, 0),  # over the tombstone
    ])
    assert res[0] == base + 1
    assert res[1] == base + 2
    assert res[2] == base + 3
    assert isinstance(res[3], CASRevisionMismatchError)
    assert res[3].revision == base + 3
    rev, kv = res[4]
    assert rev == base + 5 and kv.value == b"v2" and kv.revision == base + 3
    assert res[5] == base + 6
    got = b.list_(b"/registry/", b"/registry0", 0, 0)
    assert [(kv.key, kv.value, kv.revision) for kv in got.kvs] == [
        (b"/registry/k", b"reborn", base + 6)]
    b.close()


def test_watch_events_strictly_ordered_across_groups():
    """Watch events stay strictly revision-ordered across group
    boundaries, with failed group members invisible (their dealt
    revisions are notified invalid, never streamed)."""
    _, b = mk_backend()
    wid, q = b.watch(b"/registry/")
    try:
        b.write_batch([
            ("create", b"/registry/w/a", b"1", None, 0),
            ("create", b"/registry/w/b", b"2", None, 0),
            ("create", b"/registry/w/a", b"dup", None, 0),  # fails, rev consumed
        ])
        b.create(b"/registry/w/c", b"3")  # sequential between groups
        b.write_batch([
            ("update", b"/registry/w/a", b"4", 1, None, 0),
            ("delete", b"/registry/w/b", 0),
        ])
        events = []
        deadline = time.time() + 10
        while len(events) < 5 and time.time() < deadline:
            batch = q.get(timeout=5)
            assert batch is not None
            events.extend(batch)
        revs = [e.revision for e in events]
        assert revs == sorted(revs) and len(set(revs)) == len(revs)
        assert [(e.key, e.verb.name, e.revision) for e in events] == [
            (b"/registry/w/a", "CREATE", 1),
            (b"/registry/w/b", "CREATE", 2),
            (b"/registry/w/c", "CREATE", 4),
            (b"/registry/w/a", "PUT", 5),
            (b"/registry/w/b", "DELETE", 6),
        ]
    finally:
        b.unwatch(wid)
        b.close()


def test_scheduler_group_formation_byte_identity():
    """Plug a depth-1 scheduler's slot, queue 8 writes, release: they
    must ride ONE commit group (write_batched > 0, one batch-size
    histogram sample) and equal the sequential oracle byte for byte."""
    from kubebrain_tpu.sched import Lane, SchedConfig, ensure_scheduler

    _, b = mk_backend()
    _, oracle = mk_backend()
    sched = ensure_scheduler(b, SchedConfig(depth=1, write_batch=8))
    assert sched.config.write_batch == 8

    release = threading.Event()
    sched.submit_async(release.wait, Lane.SYSTEM)
    time.sleep(0.1)

    keys = [b"/registry/pods/g/p-%d" % i for i in range(8)]
    outs: dict = {}

    def one(i):
        # distinct clients: queue arrival order == submission index order
        # is NOT guaranteed across clients, so ops commute (disjoint keys)
        outs[i] = sched.create(keys[i], b"val-%d" % i, client="c%d" % i)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    release.set()
    for t in threads:
        t.join(30)
    assert sched.write_batched > 0, "plugged slot formed no write group"
    assert sorted(outs) == list(range(8))

    for i in range(8):
        oracle.create(keys[i], b"val-%d" % i)
    # disjoint keys: the final value set matches; revisions are a
    # contiguous block in both worlds
    got = sorted((kv.key, kv.value) for kv in
                 b.list_(b"/registry/pods/g/", b"/registry/pods/g0", 0, 0).kvs)
    want = sorted((kv.key, kv.value) for kv in
                  oracle.list_(b"/registry/pods/g/",
                               b"/registry/pods/g0", 0, 0).kvs)
    assert got == want
    assert sorted(outs.values()) == list(
        range(min(outs.values()), min(outs.values()) + 8))
    b.close()
    oracle.close()


def test_scheduler_per_client_fifo_within_groups():
    """Same-client writes keep submission order even when drained into
    groups: a client's create->update->update chain on one key must land
    in order (each CAS sees its predecessor), across many clients."""
    from kubebrain_tpu.sched import SchedConfig, ensure_scheduler

    _, b = mk_backend()
    sched = ensure_scheduler(b, SchedConfig(depth=2, write_batch=8))
    errs: list = []

    def client(ci):
        try:
            k = b"/registry/fifo/c-%d" % ci
            rev = sched.create(k, b"v0", client=f"c{ci}")
            for step in range(6):
                rev = sched.update(k, b"v%d" % (step + 1), rev,
                                   client=f"c{ci}")
        except BaseException as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errs, errs[0]
    res = b.list_(b"/registry/fifo/", b"/registry/fifo0", 0, 0)
    assert len(res.kvs) == 8
    assert all(kv.value == b"v6" for kv in res.kvs)
    b.close()


class _NoBatchStore:
    """Engine shim hiding ``write_batch``: forces the per-op fallback."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        if name == "write_batch":
            raise AttributeError(name)
        return getattr(self._inner, name)


def test_engine_without_write_batch_falls_back_per_op():
    rng = np.random.RandomState(3)
    ops = gen_ops(rng, 80)
    _, plain = mk_backend(store=_NoBatchStore(new_storage("memkv")))
    _, seq = mk_backend()
    assert plain._engine_write_batch is None
    got = [fp_op_result(r) for r in plain.write_batch(list(ops))]
    want = []
    for op in ops:
        try:
            want.append(fp_op_result(seq._apply_single(op)))
        except BaseException as e:
            want.append(fp_op_result(e))
    assert got == want
    assert fp_state(plain) == fp_state(seq)
    plain.close()
    seq.close()


def test_demux_failure_cannot_strand_the_revision_block():
    """A transient engine error while demuxing one op's outcome (here:
    reading a CAS conflict's latest value) fails ONLY that op — the
    block's events still reach the ring and the sequencer advances, so
    later writes proceed. Regression: a demux exception escaped
    Backend.write_batch before _notify_many, stranding the dealt block
    and stalling every subsequent write behind the sequencer."""
    from kubebrain_tpu.storage.errors import StorageError

    _, b = mk_backend()
    r1 = b.create(b"/registry/a", b"v1")
    r2 = b.update(b"/registry/a", b"v2", r1)  # r1 is now truly stale

    def flaky_read(key, rev):
        raise StorageError("transient wire error")

    orig, b._read_object = b._read_object, flaky_read
    try:
        res = b.write_batch([
            ("update", b"/registry/a", b"x", r1, None, 0),  # CAS conflict
            ("create", b"/registry/b", b"v2", None, 0),
        ])
    finally:
        b._read_object = orig
    assert isinstance(res[0], StorageError)
    assert res[1] == r2 + 2  # the conflict consumed r2+1, create landed after
    # the sequencer advanced past the whole block: a later write completes
    assert b.create(b"/registry/c", b"v3") == r2 + 3
    b.close()


def test_tso_deal_block_contiguous_under_race():
    from kubebrain_tpu.backend.tso import TSO

    tso = TSO()
    blocks: list = []
    lock = threading.Lock()

    def dealer():
        for _ in range(50):
            first = tso.deal_block(3)
            with lock:
                blocks.append((first, 3))
            tso.commit(first + 2)

    threads = [threading.Thread(target=dealer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    spans = sorted(blocks)
    covered = []
    for first, n in spans:
        covered.extend(range(first, first + n))
    assert covered == list(range(1, 601)), "blocks must tile with no overlap"
    with pytest.raises(ValueError):
        tso.deal_block(0)


# ---------------------------------------------------------------- TPU merge
def mk_tpu_backend(ndev, partitions=0, kernel="jnp", merge_threshold=64):
    mesh = make_mesh(n_devices=ndev)
    store = TpuKvStorage(new_storage("memkv"), mesh=mesh,
                         partitions=partitions)
    b = Backend(store, BackendConfig(event_ring_capacity=16384))
    b.scanner._host_limit_threshold = 0  # always the device path
    b.scanner._merge_threshold = merge_threshold
    b.scanner._scan_kernel = kernel
    b.scanner._kernel_mesh = mesh if kernel != "jnp" else None
    return b


def churn(b: Backend, rng, steps, keyspace=60, live=None):
    live = {} if live is None else live
    for step in range(steps):
        k = b"/registry/pods/ns-%d/p-%03d" % (step % 4, rng.randint(keyspace))
        if k not in live:
            live[k] = b.create(k, b"v%04d" % step)
        elif rng.rand() < 0.6:
            live[k] = b.update(k, b"u%04d" % step, live[k])
        else:
            b.delete(k, live.pop(k))


@pytest.mark.parametrize("kernel", ["jnp", "pallas_interpret"])
@pytest.mark.parametrize("ndev,parts", [(8, 0), (4, 8)])
def test_incremental_merge_vs_full_rebuild_identity(kernel, ndev, parts):
    """Churn through a low merge threshold (many incremental stored-
    domain merges) vs a twin whose every publish is a full store rebuild:
    reads must agree byte for byte at head AND at snapshots, and the
    incremental engine's accounting must show NO full rebuild — every
    delta row accounted by merge_rows_total."""
    inc = mk_tpu_backend(ndev, partitions=parts, kernel=kernel,
                         merge_threshold=32)
    full = mk_tpu_backend(ndev, partitions=parts, kernel=kernel,
                          merge_threshold=10**9)  # delta overlay stays live
    try:
        rng = np.random.RandomState(19)
        live: dict[bytes, int] = {}
        checkpoints: list[int] = []
        for i in range(40):  # seed, then publish: merges need a mirror
            k = b"/registry/pods/ns-%d/p-%03d" % (i % 4, i)
            for be in (inc, full):
                r = be.create(k, b"seed")
            live[k] = r
        inc.scanner.publish()
        full.scanner.publish()
        for step in range(300):
            k = b"/registry/pods/ns-%d/p-%03d" % (step % 4, rng.randint(60))
            if k not in live:
                for be in (inc, full):
                    r = be.create(k, b"v%04d" % step)
                live[k] = r
            elif rng.rand() < 0.6:
                for be in (inc, full):
                    r = be.update(k, b"u%04d" % step, live[k])
                live[k] = r
            else:
                for be in (inc, full):
                    be.delete(k, live[k])
                live.pop(k)
            if step % 10 == 3:
                # reads cross the merge threshold naturally on `inc`; the
                # twin keeps everything in its live overlay
                inc.count(b"/registry/pods/", b"/registry/pods0")
            if step % 60 == 30:
                checkpoints.append(inc.current_revision())
        inc.scanner.publish()
        full.scanner._force_rebuild = True  # twin: one full store rebuild
        full.scanner.publish()

        sc = inc.scanner
        assert sc.merge_count > 0, "threshold crossings must have merged"
        assert sc.full_rebuild_total == 0, \
            "steady-state churn must never take the full-rebuild path"
        assert sc.merge_rows_total > 0

        for ns in range(4):
            s = b"/registry/pods/ns-%d/" % ns
            e = b"/registry/pods/ns-%d0" % ns
            for rev in [0, *checkpoints]:
                a = inc.list_(s, e, rev, 0)
                bres = full.list_(s, e, rev, 0)
                assert [(kv.key, kv.value, kv.revision) for kv in a.kvs] == \
                    [(kv.key, kv.value, kv.revision) for kv in bres.kvs], \
                    (kernel, ndev, parts, ns, rev)
                assert inc.count(s, e, rev) == full.count(s, e, rev)
    finally:
        inc.close()
        full.close()


def test_incremental_merge_runs_off_engine_lock(monkeypatch):
    """Readers are NOT blocked behind the merge interleave: while one
    thread sits inside the heavy merge step (off ``_mlock``), a reader on
    another thread completes. (Regression shape: the old _merge_delta
    rebuilt host-side under the engine lock, stalling every read for the
    whole rebuild.)"""
    from kubebrain_tpu.storage.tpu import engine as engine_mod

    b = mk_tpu_backend(8, merge_threshold=10**9)
    try:
        for i in range(200):
            b.create(b"/registry/off/k%04d" % i, b"v")
        b.scanner.publish()
        for i in range(500):
            b.create(b"/registry/off/m%04d" % i, b"v")

        sc = b.scanner
        entered = threading.Event()
        release = threading.Event()
        real = engine_mod.merge_partitions_stored

        def slow_merge(*args, **kwargs):
            entered.set()
            release.wait(10)
            return real(*args, **kwargs)

        monkeypatch.setattr(engine_mod, "merge_partitions_stored", slow_merge)
        merger = threading.Thread(target=sc._merge_delta)
        merger.start()
        assert entered.wait(10), "merge never started"
        done = threading.Event()
        got: list = []

        def read():
            got.append(b.count(b"/registry/off/", b"/registry/off0"))
            done.set()

        reader = threading.Thread(target=read)
        reader.start()
        finished = done.wait(8)
        release.set()
        merger.join(30)
        reader.join(10)
        assert finished, "reader stalled behind the off-lock merge"
        assert got and got[0][0] == 700
        # post-merge reads still exact
        assert b.count(b"/registry/off/", b"/registry/off0")[0] == 700
    finally:
        b.close()


def test_merge_metrics_emitted():
    """kb_mirror_merge_seconds{kind=incremental} + merge_rows_total move
    on an incremental merge; kb_sched_write_batch_size moves on group
    formation."""
    from prometheus_client import generate_latest

    from kubebrain_tpu.metrics.prom import PrometheusMetrics

    m = PrometheusMetrics()
    b = mk_tpu_backend(8, merge_threshold=16)
    b.scanner.register_metrics(m)
    try:
        rng = np.random.RandomState(5)
        # seed the SAME keyspace churn writes into, so delta rows spread
        # across partitions instead of overflowing one (which would take
        # the full-rebuild path this test asserts against)
        seeded = {}
        for ns in range(4):
            for i in range(0, 60, 2):
                k = b"/registry/pods/ns-%d/p-%03d" % (ns, i)
                seeded[k] = b.create(k, b"s")
        b.scanner.publish()
        churn(b, rng, 120, live=seeded)
        b.scanner.publish()
        text = generate_latest(m.registry).decode()
        assert 'kb_mirror_merge_seconds_count{kind="incremental"}' in text
        rows = [line for line in text.splitlines()
                if line.startswith("kb_mirror_merge_rows_total ")
                or line.startswith("kb_mirror_merge_rows_total_total ")]
        assert rows and float(rows[0].split()[-1]) > 0
        assert b.scanner.merge_rows_total == float(rows[0].split()[-1])
    finally:
        b.close()


def test_post_compact_merge_stays_incremental():
    """compact() must bind its fresh delta to the NEW mirror's stored
    domain (key width + encoding): the next threshold merge stays
    incremental. Regression: compact reset the delta with a bare
    _DeltaIndex(), so post-compact sealed blocks were raw default-width
    and the width check forced a full rebuild on every merge after a
    compaction."""
    b = mk_tpu_backend(8, merge_threshold=16)
    try:
        rng = np.random.RandomState(11)
        seeded = {}
        for ns in range(4):
            for i in range(0, 60, 2):
                k = b"/registry/pods/ns-%d/p-%03d" % (ns, i)
                seeded[k] = b.create(k, b"s")
        b.scanner.publish()
        churn(b, rng, 60, live=seeded)
        b.scanner.publish()
        assert b.scanner.full_rebuild_total == 0
        b.compact(b.current_revision() - 1)
        churn(b, rng, 60, live=seeded)
        b.scanner.publish()
        assert b.scanner.full_rebuild_total == 0, \
            "post-compact merge took the full-rebuild path"
        assert b.scanner.merge_rows_total > 0
    finally:
        b.close()


def test_group_commit_through_tpu_engine_records_delta_once():
    """A grouped commit over the TPU engine lands ALL its rows in the
    delta in revision order (one _on_committed call), and subsequent
    device reads see them — grouped == sequential over the mirror too."""
    b = mk_tpu_backend(8, merge_threshold=10**9)
    try:
        b.create(b"/registry/gd/seed", b"s")
        b.scanner.publish()
        base = b.current_revision()
        res = b.write_batch([
            ("create", b"/registry/gd/a", b"1", None, 0),
            ("create", b"/registry/gd/b", b"2", None, 0),
            ("update", b"/registry/gd/a", b"3", base + 1, None, 0),
            ("delete", b"/registry/gd/b", 0),
        ])
        assert res[:3] == [base + 1, base + 2, base + 3]
        got = b.list_(b"/registry/gd/", b"/registry/gd0", 0, 0)
        assert [(kv.key, kv.value, kv.revision) for kv in got.kvs] == [
            (b"/registry/gd/a", b"3", base + 3),
            (b"/registry/gd/seed", b"s", base),
        ]
        # delta rows arrived in revision order (merge-sort precondition)
        revs = [r for (_, r, _) in b.scanner._delta.rows()]
        assert revs == sorted(revs)
    finally:
        b.close()
