#!/usr/bin/env bash
# The offline CI entry point (mirrored by .github/workflows/check.yml):
#   1. make lint        — kblint project invariants (syntactic KB101-KB111
#                         + the funnel-confinement rules KB116/KB117/KB127
#                         + the --deep interprocedural tier KB112-KB122
#                         + the CFG/typestate leak tier KB123-KB126,
#                         zero non-baselined findings, <60s budget
#                         enforced) + native lint, then the kblint engine
#                         self-tests (rule fixtures, differential corpus,
#                         leak-rule corpus, cache cold/warm) — a lint-engine
#                         regression should fail before anything else runs
#   2. make typecheck   — mypy (or compileall fallback)
#   3. scheduler gate   — sched semantics + query-batched scan tests
#                         (batched == sequential byte-identical, incl. the
#                         batched Pallas kernel cases) + bench-smoke; fast,
#                         and a scheduler regression should fail before the
#                         long tier-1 run, not 10 minutes into it
#   4. observability    — trace/span tests + a live-server smoke: one Range
#                         must populate /debug/traces and the
#                         kb_rpc_stage_seconds histogram
#   5. lease subsystem  — TTL state machine + revision-stamped expiry
#                         (a lease regression silently breaks apiserver
#                         event TTLs; fail before the long tier-1 run)
#   6. workload replay  — generator determinism (same seed => byte-identical
#                         op trace), SLO report schema, and a small-N
#                         end-to-end replay through the real gRPC front
#                         with client/server /metrics reconciliation
#   7. multichip+encode — sharded serving on 8 simulated host devices
#                         (conftest's xla_force_host_platform_device_count):
#                         sharded-vs-single byte identity, O(visible-rows)
#                         host transfer, dirty-shard-only republish, the
#                         served dry-run emitting multichip_rows_per_sec,
#                         and the encoded-mirror differential suite
#                         (encoded == raw byte-identity incl. overlays,
#                         adversarial bounds, pallas-vs-jnp, P=N/P=2N)
#   8. compaction       — device-side stored-domain compaction
#                         (docs/compaction.md): differential vs the
#                         engine-generic compactor, victim-only decode,
#                         dirty-shard-only republish, retry→escalate, and
#                         a small-N bench-compact smoke asserting store +
#                         serving byte-identity vs the sequential oracle
#   9. replica          — read scale-out (docs/replication.md): follower
#                         fence-read correctness (byte-identical to the
#                         leader under concurrent writers), bounded-
#                         staleness refusal + the degradation ladder,
#                         watch resume across a replication reset, the
#                         TPU-mirror identity at pinned revisions, and a
#                         small two-replica end-to-end smoke through the
#                         real gRPC front
#  10. chaos (FAULTS)   — deterministic fault injection (docs/faults.md):
#                         schedule sha determinism, FAULTS=none inertness
#                         byte-identity, the storage error taxonomy through
#                         a live Backend (definite/uncertain + group-commit
#                         demux + FIFO read-back repair), mirror quarantine/
#                         merge-retry/escalation, watch resume (no lost or
#                         duplicated events across server-side resets), and
#                         a small FAULTS=smoke replay asserting the
#                         acknowledged-write consistency invariant
#  11. watch fan-out    — block-batched dispatch (docs/watch.md): device
#                         deliver byte-identical to the brute-force and
#                         segment-index oracles under churn, the sharded
#                         wat-table identity on 8 simulated devices,
#                         NUL-bound single-key exactness, overflow regrow,
#                         version-regression rebuild, KB127 confinement
#                         self-tests (via step 1), and bench-fanout at the
#                         full 10k-watcher acceptance config enforcing the
#                         >=2x block-vs-per-batch bar plus the live-hub
#                         lag p99 bar
#  12. tier-1 pytest    — the ROADMAP.md verify command
# Run from anywhere; operates on the repo this script lives in.

set -uo pipefail

cd "$(dirname "$0")/.."

echo "=== [1/12] make lint (syntactic + deep interprocedural, 60s budget)"
make lint || exit 1
env JAX_PLATFORMS=cpu python -m pytest tests/test_kblint.py \
    tests/test_kblint_deep.py tests/test_kblint_races.py \
    tests/test_kblint_leaks.py \
    -q -m 'not slow' -p no:cacheprovider || exit 1

echo "=== [2/12] make typecheck"
make typecheck || exit 1

echo "=== [3/12] scheduler semantics + query-batched scan + write group commit + bench-smoke (CPU fallback)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_sched.py \
    tests/test_sched_batch.py tests/test_scan_pallas.py \
    tests/test_write_batch.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1
# runtime field-write sanitizer smoke (docs/static_analysis.md): the
# concurrency-heavy write-path module under KB_FIELDCHECK=1 — the
# instrumented __setattr__ path must neither break the suite nor record
# ungated multi-thread no-common-guard writes on the tracked classes
env JAX_PLATFORMS=cpu KB_FIELDCHECK=1 KB_FIELDCHECK_STRICT=1 \
    python -m pytest tests/test_write_batch.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1
make bench-smoke || exit 1

echo "=== [4/12] request tracing: span tests + live-server /debug/traces smoke"
env JAX_PLATFORMS=cpu python -m pytest tests/test_trace.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1
env JAX_PLATFORMS=cpu python tools/smoke_trace.py || exit 1

echo "=== [5/12] lease subsystem: TTL state machine + revision-stamped expiry"
env JAX_PLATFORMS=cpu python -m pytest tests/test_lease.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1

echo "=== [6/12] workload replay: determinism + SLO schema + small-N gRPC smoke"
env JAX_PLATFORMS=cpu python -m pytest tests/test_workload.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1

echo "=== [7/12] multichip sharded serving + encoded mirror: identity + transfer budget + served dry-run"
env JAX_PLATFORMS=cpu python -m pytest tests/test_multichip.py \
    tests/test_encode.py \
    tests/test_graft_entry.py -q -m 'not slow' -p no:cacheprovider || exit 1

echo "=== [8/12] device-side compaction: stored-domain differential + victim-only decode + bench-compact smoke"
env JAX_PLATFORMS=cpu python -m pytest tests/test_compact_device.py \
    tests/test_compact_faults.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1
env JAX_PLATFORMS=cpu KB_BENCH_METRIC=compact KB_BENCH_KEYS=4000 \
    python bench.py || exit 1

echo "=== [9/12] replica: fence reads + bounded staleness + watch resume + two-replica gRPC smoke"
env JAX_PLATFORMS=cpu python -m pytest tests/test_replica.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1

echo "=== [10/12] chaos: fault-schedule determinism + inertness + taxonomy + FAULTS=smoke consistency gate"
env JAX_PLATFORMS=cpu python -m pytest tests/test_faults.py \
    tests/test_watch_robustness.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1
# chaos under the full sanitizer umbrella (docs/static_analysis.md): the
# fault-injection suite with lockcheck + fieldcheck + leakcheck all armed
# and strict — exception paths under injected faults must not leak dealt
# revisions, slots, watchers, or spans (the KB123-KB126 runtime twin)
env JAX_PLATFORMS=cpu KB_SANITIZE=1 KB_SANITIZE_STRICT=1 \
    python -m pytest tests/test_faults.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1

echo "=== [11/12] watch fan-out: block-batched dispatch differentials + sharded wat table + bench-fanout bars"
env JAX_PLATFORMS=cpu python -m pytest tests/test_fanout_device.py \
    tests/test_fanout_integration.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1
# bench-fanout at the full acceptance config (docs/watch.md; ~25s on one
# CPU core — the >=2x block-vs-per-batch bar is defined at 10k watchers
# and small-N would let fixed overheads eat it): identity vs the brute
# and segment-index oracles, the speedup bar, the live-hub lag p99 bar;
# the report lands in /tmp, not the repo
env JAX_PLATFORMS=cpu KB_BENCH_METRIC=fanout \
    KB_FANOUT_OUT=/tmp/FANOUT_ci.json \
    python bench.py || exit 1

echo "=== [12/12] tier-1 tests (ROADMAP.md verify, one definition: make test-tier1)"
exec make test-tier1
