#!/usr/bin/env bash
# The offline CI entry point (mirrored by .github/workflows/check.yml):
#   1. make lint        — kblint project invariants + native lint
#   2. make typecheck   — mypy (or compileall fallback)
#   3. scheduler gate   — sched semantics + query-batched scan tests
#                         (batched == sequential byte-identical, incl. the
#                         batched Pallas kernel cases) + bench-smoke; fast,
#                         and a scheduler regression should fail before the
#                         long tier-1 run, not 10 minutes into it
#   4. observability    — trace/span tests + a live-server smoke: one Range
#                         must populate /debug/traces and the
#                         kb_rpc_stage_seconds histogram
#   5. lease subsystem  — TTL state machine + revision-stamped expiry
#                         (a lease regression silently breaks apiserver
#                         event TTLs; fail before the long tier-1 run)
#   6. tier-1 pytest    — the ROADMAP.md verify command
# Run from anywhere; operates on the repo this script lives in.

set -uo pipefail

cd "$(dirname "$0")/.."

echo "=== [1/6] make lint"
make lint || exit 1

echo "=== [2/6] make typecheck"
make typecheck || exit 1

echo "=== [3/6] scheduler semantics + query-batched scan + bench-smoke (CPU fallback)"
env JAX_PLATFORMS=cpu python -m pytest tests/test_sched.py \
    tests/test_sched_batch.py tests/test_scan_pallas.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1
make bench-smoke || exit 1

echo "=== [4/6] request tracing: span tests + live-server /debug/traces smoke"
env JAX_PLATFORMS=cpu python -m pytest tests/test_trace.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1
env JAX_PLATFORMS=cpu python tools/smoke_trace.py || exit 1

echo "=== [5/6] lease subsystem: TTL state machine + revision-stamped expiry"
env JAX_PLATFORMS=cpu python -m pytest tests/test_lease.py -q -m 'not slow' \
    -p no:cacheprovider || exit 1

echo "=== [6/6] tier-1 tests (ROADMAP.md verify, one definition: make test-tier1)"
exec make test-tier1
