#!/usr/bin/env bash
# The offline CI entry point (mirrored by .github/workflows/check.yml):
#   1. make lint        — kblint project invariants + native lint
#   2. make typecheck   — mypy (or compileall fallback)
#   3. tier-1 pytest    — the ROADMAP.md verify command
# Run from anywhere; operates on the repo this script lives in.

set -uo pipefail

cd "$(dirname "$0")/.."

echo "=== [1/3] make lint"
make lint || exit 1

echo "=== [2/3] make typecheck"
make typecheck || exit 1

echo "=== [3/3] tier-1 tests (ROADMAP.md verify, one definition: make test-tier1)"
exec make test-tier1
