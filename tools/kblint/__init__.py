"""kblint: project-invariant static analysis for kubebrain-tpu.

The test suite samples the project's correctness invariants; kblint checks
them on every line. Each rule encodes one invariant the architecture
depends on (see docs/static_analysis.md for the full catalogue):

- KB101  no blocking calls inside ``async def`` bodies (endpoint/, server/)
- KB102  no JAX dispatch / RPC / sleeps while holding a ``threading.Lock``
- KB103  no bare ``except:``
- KB104  no host synchronization inside ``@jax.jit`` kernels (ops/)
- KB105  revision arithmetic must flow through server/service/revision.py

Suppress a finding with a trailing comment on the flagged line (or on the
enclosing ``with``/``def`` header for block rules)::

    subprocess.Popen(...)  # kblint: disable=KB101 -- one-shot startup fork

Run as ``python -m tools.kblint [paths...]``.
"""

from .core import Finding, Rule, RULES, lint_paths, lint_source, register

__all__ = ["Finding", "Rule", "RULES", "lint_paths", "lint_source", "register"]
