"""kblint: project-invariant static analysis for kubebrain-tpu.

Two tiers (see docs/static_analysis.md for the full catalogue):

**Syntactic** (per-file AST rules, always on):

- KB101  no blocking calls inside ``async def`` bodies (endpoint/, server/)
- KB102  no JAX dispatch / RPC / sleeps while holding a ``threading.Lock``
- KB103  no bare ``except:``
- KB104  no host synchronization inside ``@jax.jit`` kernels (ops/)
- KB105  revision arithmetic must flow through server/service/revision.py
- KB106  service-layer range reads go through the request scheduler
- KB107  no print()/raw time.time() latency math on the serving path
- KB108  TTL/deadline arithmetic only via kubebrain_tpu/lease/clock.py
- KB109  scan kernels dispatch only from the _dev_mask assembly points
- KB110  workload/ stays replayable (no unseeded RNG, no time.time())
- KB111  storage/tpu/ device→host pulls only at named materialization points
- KB116  encoded-key decode only through the decoded_keys/user_key funnels,
  themselves only from the named materialization/rebuild paths
- KB117  query-bound packing/encoding only inside the domain-dispatch
  funnels — kernels never see a bound from the wrong key domain

**Interprocedural** (``--deep``: whole-program call graph + context
propagation over kubebrain_tpu/ + tools/ + bench.py; graph.py/contexts.py):

- KB112  blocking call *transitively* reachable while a lock is held
- KB113  host sync *transitively* reachable from jit/shard_map-traced code
- KB114  device-array taint escaping to host outside the KB111 allowlist
  (catches alias/wrapper laundering the name-based KB111 misses by design)
- KB115  static lock-acquisition-order graph must be acyclic (cross-checked
  against util/lockcheck.py's runtime-observed edges)

Pre-existing deep findings are pinned in tools/kblint/baseline.json, not
silenced; per-file results are cached content-hash-keyed in .kblint_cache/.

Suppress a finding with a trailing comment on the flagged line (or on the
enclosing ``with``/``def`` header for syntactic block rules)::

    subprocess.Popen(...)  # kblint: disable=KB101 -- one-shot startup fork

Run as ``python -m tools.kblint [paths...] [--deep]``.
"""

from .core import (Baseline, Finding, Rule, RULES, deep_analyze_paths,
                   deep_analyze_sources, lint_paths, lint_source, register)

__all__ = ["Baseline", "Finding", "Rule", "RULES", "deep_analyze_paths",
           "deep_analyze_sources", "lint_paths", "lint_source", "register"]
