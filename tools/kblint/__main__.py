"""CLI: ``python -m tools.kblint [paths...] [--deep] [--list-rules]``.

Two tiers (docs/static_analysis.md):

- default: the syntactic per-file rules KB101–KB111 over ``paths``
- ``--deep``: additionally builds the whole-program call graph over
  ``kubebrain_tpu/ + tools/ + bench.py`` and runs the interprocedural
  rules KB112–KB122 plus the CFG/typestate leak rules KB123–KB126,
  filtered through tools/kblint/baseline.json and held to a wall-clock
  budget (CI fails if the analysis outgrows it).

``--sarif PATH`` additionally writes the run's findings as SARIF 2.1.0
for GitHub code scanning (baselined findings ride along marked
``unchanged``).

Both tiers share the content-hash cache in ``.kblint_cache/`` (disable
with ``KBLINT_CACHE=0``), so incremental runs only re-analyze edited
files.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import rules  # noqa: F401  -- importing registers the rules
from .cache import LintCache
from .core import (Baseline, DEEP_ROOTS, RULES, deep_analyze_paths,
                   lint_paths)

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")
DEFAULT_BUDGET = 60.0  # seconds: the stated CI wall-clock budget


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kblint", description="kubebrain-tpu project-invariant linter"
    )
    parser.add_argument("paths", nargs="*", default=["kubebrain_tpu"],
                        help="files or directories to lint (syntactic tier)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    parser.add_argument("--root", default=os.getcwd(),
                        help="repo root for relative paths (default: cwd)")
    parser.add_argument("--deep", action="store_true",
                        help="run the interprocedural tier (KB112-KB122) "
                             "over kubebrain_tpu/ + tools/ + bench.py")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON pinning pre-existing deep "
                             "findings (default: tools/kblint/baseline.json)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from the current deep "
                             "findings (preserves justifications)")
    parser.add_argument("--budget", type=float, default=DEFAULT_BUDGET,
                        help="wall-clock budget in seconds for the whole "
                             "run; exceeded = nonzero exit (default 60)")
    parser.add_argument("--lock-edges", default="",
                        help="JSON file of runtime lock-order edges "
                             "(util/lockcheck.py export) to cross-check "
                             "against the static KB115 graph; defaults to "
                             "$KBLINT_LOCK_EDGES on --deep runs")
    parser.add_argument("--lock-graph", action="store_true",
                        help="print the static lock-order graph and the "
                             "runtime cross-check report")
    parser.add_argument("--field-observed", default="",
                        help="JSON file of runtime field-guard observations "
                             "(util/fieldcheck.py export) to cross-check "
                             "against the static KB120 guard inference; "
                             "defaults to $KBLINT_FIELD_OBSERVED on --deep "
                             "runs")
    parser.add_argument("--field-guards", action="store_true",
                        help="print the static field-guard report and the "
                             "runtime fieldcheck cross-check")
    parser.add_argument("--leak-observed", default="",
                        help="JSON file of runtime leak observations "
                             "(util/leakcheck.py export) to cross-check "
                             "against the static KB123-KB126 obligation "
                             "sites; defaults to $KBLINT_LEAK_OBSERVED on "
                             "--deep runs")
    parser.add_argument("--leak-report", action="store_true",
                        help="print the static obligation-site report and "
                             "the runtime leakcheck cross-check")
    parser.add_argument("--sarif", default="",
                        help="write findings as SARIF 2.1.0 to this path "
                             "(for GitHub code-scanning upload)")
    parser.add_argument("--stats", action="store_true",
                        help="print resolution/propagation statistics")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass .kblint_cache/ for this run")
    args = parser.parse_args(argv)

    if args.list_rules:
        from .contexts import DEEP_RULES
        for rid in sorted(RULES):
            print(f"{rid}  {RULES[rid].summary}")
        for rid in sorted(DEEP_RULES):
            print(f"{rid}  {DEEP_RULES[rid]} [--deep]")
        return 0

    if not args.deep and (args.lock_edges or args.lock_graph or args.stats
                          or args.write_baseline or args.field_observed
                          or args.field_guards or args.leak_observed
                          or args.leak_report):
        # a typo'd CI line must not pass green while doing none of the work
        # (only EXPLICIT flags trigger this — the KBLINT_LOCK_EDGES /
        # KBLINT_FIELD_OBSERVED / KBLINT_LEAK_OBSERVED env fallbacks are
        # read later, on --deep runs only, so an exported env var cannot
        # fail an ordinary syntactic run). --sarif is fine without --deep:
        # a syntactic-only SARIF is still a complete scan of its tier.
        print("kblint: --lock-edges/--lock-graph/--field-observed/"
              "--field-guards/--leak-observed/--leak-report/--stats/"
              "--write-baseline require --deep", file=sys.stderr)
        return 2
    if args.deep and not args.lock_edges:
        args.lock_edges = os.environ.get("KBLINT_LOCK_EDGES", "")
    if args.deep and not args.field_observed:
        args.field_observed = os.environ.get("KBLINT_FIELD_OBSERVED", "")
    if args.deep and not args.leak_observed:
        args.leak_observed = os.environ.get("KBLINT_LEAK_OBSERVED", "")

    t0 = time.monotonic()
    cache = None if args.no_cache else LintCache.from_env(args.root)
    findings = lint_paths(args.paths or ["kubebrain_tpu"], root=args.root,
                          cache=cache)
    failed = False
    for f in findings:
        print(f.format())
    if findings:
        print(f"kblint: {len(findings)} finding(s)", file=sys.stderr)
        failed = True
    sarif_new = list(findings)
    sarif_pinned: list = []

    if args.deep:
        runtime_edges = None
        if args.lock_edges:
            try:
                with open(args.lock_edges, encoding="utf-8") as fh:
                    runtime_edges = [tuple(e) for e in
                                     json.load(fh).get("edges", [])]
            except (OSError, ValueError) as e:
                print(f"kblint: unreadable --lock-edges file: {e}",
                      file=sys.stderr)
                return 2
        field_obs = None
        if args.field_observed:
            try:
                with open(args.field_observed, encoding="utf-8") as fh:
                    data = json.load(fh)
                if not isinstance(data, dict):
                    raise ValueError(
                        "expected the export_observed() object form "
                        "({'fields': [...]}), got "
                        + type(data).__name__)
                field_obs = list(data.get("fields", []))
            except (OSError, ValueError) as e:
                print(f"kblint: unreadable --field-observed file: {e}",
                      file=sys.stderr)
                return 2
        leak_obs = None
        if args.leak_observed:
            try:
                with open(args.leak_observed, encoding="utf-8") as fh:
                    data = json.load(fh)
                if not isinstance(data, dict):
                    raise ValueError(
                        "expected the export_observed() object form "
                        "({'kinds': [...]}), got " + type(data).__name__)
                leak_obs = list(data.get("kinds", []))
            except (OSError, ValueError) as e:
                print(f"kblint: unreadable --leak-observed file: {e}",
                      file=sys.stderr)
                return 2
        result = deep_analyze_paths(args.root, DEEP_ROOTS, cache=cache,
                                    runtime_lock_edges=runtime_edges,
                                    runtime_field_obs=field_obs,
                                    runtime_leak_obs=leak_obs)
        baseline = Baseline.load(args.baseline)
        new, pinned, stale = baseline.split(result.findings)
        if args.write_baseline:
            Baseline.write(args.baseline, result.findings, previous=baseline)
            print(f"kblint-deep: wrote {len(result.findings)} finding(s) to "
                  f"{args.baseline}")
            new = []
        for f in new:
            print(f.format())
        if new:
            print(f"kblint-deep: {len(new)} non-baselined finding(s)",
                  file=sys.stderr)
            failed = True
        if stale and not args.write_baseline:  # the write just cleaned them
            print(f"kblint-deep: note: {len(stale)} stale baseline "
                  f"entr{'y' if len(stale) == 1 else 'ies'} no longer "
                  f"fire(s) — clean with --write-baseline", file=sys.stderr)
        s = result.stats
        print(f"kblint-deep: {s['files']} modules, {s['functions']} "
              f"functions, {s['resolved_calls']} calls resolved / "
              f"{s['unresolved_calls']} unresolved / {s['fn_refs']} fn-refs,"
              f" {len(pinned)} baselined, {s['lock_edges']} lock edges, "
              f"{s.get('leak_acquires', 0)} leak obligations, "
              f"{s['elapsed_seconds']}s")
        if args.stats:
            print(json.dumps(s, indent=1, sort_keys=True))
        if args.lock_graph:
            print(json.dumps(result.lock_graph, indent=1, sort_keys=True))
        if args.field_guards:
            print(json.dumps(result.field_guards, indent=1, sort_keys=True))
        if args.leak_report:
            print(json.dumps(result.leaks, indent=1, sort_keys=True))
        sarif_new.extend(new)
        sarif_pinned = list(pinned)

    if args.sarif:
        from .sarif import write_sarif
        write_sarif(args.sarif, sarif_new, sarif_pinned)
        print(f"kblint: wrote SARIF ({len(sarif_new)} result(s), "
              f"{len(sarif_pinned)} baselined) to {args.sarif}",
              file=sys.stderr)

    elapsed = time.monotonic() - t0
    if args.budget and elapsed > args.budget:
        print(f"kblint: BUDGET EXCEEDED: {elapsed:.1f}s > {args.budget:.0f}s"
              " — the analysis must stay inside the CI wall-clock budget",
              file=sys.stderr)
        return 2
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
