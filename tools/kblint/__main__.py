"""CLI: ``python -m tools.kblint [paths...] [--deep] [--list-rules]``.

Two tiers (docs/static_analysis.md):

- default: the syntactic per-file rules KB101–KB111 over ``paths``
- ``--deep``: additionally builds the whole-program call graph over
  ``kubebrain_tpu/ + tools/ + bench.py`` and runs the interprocedural
  rules KB112–KB122, filtered through tools/kblint/baseline.json and held
  to a wall-clock budget (CI fails if the analysis outgrows it).

Both tiers share the content-hash cache in ``.kblint_cache/`` (disable
with ``KBLINT_CACHE=0``), so incremental runs only re-analyze edited
files.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import rules  # noqa: F401  -- importing registers the rules
from .cache import LintCache
from .core import (Baseline, DEEP_ROOTS, RULES, deep_analyze_paths,
                   lint_paths)

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")
DEFAULT_BUDGET = 60.0  # seconds: the stated CI wall-clock budget


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kblint", description="kubebrain-tpu project-invariant linter"
    )
    parser.add_argument("paths", nargs="*", default=["kubebrain_tpu"],
                        help="files or directories to lint (syntactic tier)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    parser.add_argument("--root", default=os.getcwd(),
                        help="repo root for relative paths (default: cwd)")
    parser.add_argument("--deep", action="store_true",
                        help="run the interprocedural tier (KB112-KB122) "
                             "over kubebrain_tpu/ + tools/ + bench.py")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON pinning pre-existing deep "
                             "findings (default: tools/kblint/baseline.json)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from the current deep "
                             "findings (preserves justifications)")
    parser.add_argument("--budget", type=float, default=DEFAULT_BUDGET,
                        help="wall-clock budget in seconds for the whole "
                             "run; exceeded = nonzero exit (default 60)")
    parser.add_argument("--lock-edges", default="",
                        help="JSON file of runtime lock-order edges "
                             "(util/lockcheck.py export) to cross-check "
                             "against the static KB115 graph; defaults to "
                             "$KBLINT_LOCK_EDGES on --deep runs")
    parser.add_argument("--lock-graph", action="store_true",
                        help="print the static lock-order graph and the "
                             "runtime cross-check report")
    parser.add_argument("--field-observed", default="",
                        help="JSON file of runtime field-guard observations "
                             "(util/fieldcheck.py export) to cross-check "
                             "against the static KB120 guard inference; "
                             "defaults to $KBLINT_FIELD_OBSERVED on --deep "
                             "runs")
    parser.add_argument("--field-guards", action="store_true",
                        help="print the static field-guard report and the "
                             "runtime fieldcheck cross-check")
    parser.add_argument("--stats", action="store_true",
                        help="print resolution/propagation statistics")
    parser.add_argument("--no-cache", action="store_true",
                        help="bypass .kblint_cache/ for this run")
    args = parser.parse_args(argv)

    if args.list_rules:
        from .contexts import DEEP_RULES
        for rid in sorted(RULES):
            print(f"{rid}  {RULES[rid].summary}")
        for rid in sorted(DEEP_RULES):
            print(f"{rid}  {DEEP_RULES[rid]} [--deep]")
        return 0

    if not args.deep and (args.lock_edges or args.lock_graph or args.stats
                          or args.write_baseline or args.field_observed
                          or args.field_guards):
        # a typo'd CI line must not pass green while doing none of the work
        # (only EXPLICIT flags trigger this — the KBLINT_LOCK_EDGES /
        # KBLINT_FIELD_OBSERVED env fallbacks are read later, on --deep
        # runs only, so an exported env var cannot fail an ordinary
        # syntactic run)
        print("kblint: --lock-edges/--lock-graph/--field-observed/"
              "--field-guards/--stats/--write-baseline require --deep",
              file=sys.stderr)
        return 2
    if args.deep and not args.lock_edges:
        args.lock_edges = os.environ.get("KBLINT_LOCK_EDGES", "")
    if args.deep and not args.field_observed:
        args.field_observed = os.environ.get("KBLINT_FIELD_OBSERVED", "")

    t0 = time.monotonic()
    cache = None if args.no_cache else LintCache.from_env(args.root)
    findings = lint_paths(args.paths or ["kubebrain_tpu"], root=args.root,
                          cache=cache)
    failed = False
    for f in findings:
        print(f.format())
    if findings:
        print(f"kblint: {len(findings)} finding(s)", file=sys.stderr)
        failed = True

    if args.deep:
        runtime_edges = None
        if args.lock_edges:
            try:
                with open(args.lock_edges, encoding="utf-8") as fh:
                    runtime_edges = [tuple(e) for e in
                                     json.load(fh).get("edges", [])]
            except (OSError, ValueError) as e:
                print(f"kblint: unreadable --lock-edges file: {e}",
                      file=sys.stderr)
                return 2
        field_obs = None
        if args.field_observed:
            try:
                with open(args.field_observed, encoding="utf-8") as fh:
                    data = json.load(fh)
                if not isinstance(data, dict):
                    raise ValueError(
                        "expected the export_observed() object form "
                        "({'fields': [...]}), got "
                        + type(data).__name__)
                field_obs = list(data.get("fields", []))
            except (OSError, ValueError) as e:
                print(f"kblint: unreadable --field-observed file: {e}",
                      file=sys.stderr)
                return 2
        result = deep_analyze_paths(args.root, DEEP_ROOTS, cache=cache,
                                    runtime_lock_edges=runtime_edges,
                                    runtime_field_obs=field_obs)
        baseline = Baseline.load(args.baseline)
        new, pinned, stale = baseline.split(result.findings)
        if args.write_baseline:
            Baseline.write(args.baseline, result.findings, previous=baseline)
            print(f"kblint-deep: wrote {len(result.findings)} finding(s) to "
                  f"{args.baseline}")
            new = []
        for f in new:
            print(f.format())
        if new:
            print(f"kblint-deep: {len(new)} non-baselined finding(s)",
                  file=sys.stderr)
            failed = True
        if stale and not args.write_baseline:  # the write just cleaned them
            print(f"kblint-deep: note: {len(stale)} stale baseline "
                  f"entr{'y' if len(stale) == 1 else 'ies'} no longer "
                  f"fire(s) — clean with --write-baseline", file=sys.stderr)
        s = result.stats
        print(f"kblint-deep: {s['files']} modules, {s['functions']} "
              f"functions, {s['resolved_calls']} calls resolved / "
              f"{s['unresolved_calls']} unresolved / {s['fn_refs']} fn-refs,"
              f" {len(pinned)} baselined, {s['lock_edges']} lock edges, "
              f"{s['elapsed_seconds']}s")
        if args.stats:
            print(json.dumps(s, indent=1, sort_keys=True))
        if args.lock_graph:
            print(json.dumps(result.lock_graph, indent=1, sort_keys=True))
        if args.field_guards:
            print(json.dumps(result.field_guards, indent=1, sort_keys=True))

    elapsed = time.monotonic() - t0
    if args.budget and elapsed > args.budget:
        print(f"kblint: BUDGET EXCEEDED: {elapsed:.1f}s > {args.budget:.0f}s"
              " — the analysis must stay inside the CI wall-clock budget",
              file=sys.stderr)
        return 2
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
