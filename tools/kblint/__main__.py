"""CLI: ``python -m tools.kblint [paths...] [--list-rules]``."""

from __future__ import annotations

import argparse
import os
import sys

from . import rules  # noqa: F401  -- importing registers the rules
from .core import RULES, lint_paths


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kblint", description="kubebrain-tpu project-invariant linter"
    )
    parser.add_argument("paths", nargs="*", default=["kubebrain_tpu"],
                        help="files or directories to lint")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    parser.add_argument("--root", default=os.getcwd(),
                        help="repo root for relative paths (default: cwd)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid}  {RULES[rid].summary}")
        return 0

    findings = lint_paths(args.paths or ["kubebrain_tpu"], root=args.root)
    for f in findings:
        print(f.format())
    if findings:
        print(f"kblint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
