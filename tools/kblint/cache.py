"""Content-hash-keyed cache of per-file kblint results (.kblint_cache/).

Incremental ``make lint``: the expensive per-file work — AST parse,
syntactic rule sweep, and the deep tier's ModuleSummary extraction — is a
pure function of (file content, kblint engine source), so it is cached
under a key of both hashes. Editing a source file invalidates exactly that
file; editing ANY kblint module (rules.py included) rotates the engine key
and invalidates everything. The whole-program propagation phase is cheap
(graph stitching + fixpoints) and always re-runs.

Entries are JSON (no pickle: a poisoned cache must not execute), one file
per (engine, content) pair, garbage-collected whenever the engine key
rotates. Disable with ``KBLINT_CACHE=0``; relocate with
``KBLINT_CACHE_DIR``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

_ENGINE_SOURCES = ("core.py", "rules.py", "graph.py", "contexts.py",
                   "cfg.py", "sarif.py", "cache.py")


def engine_key() -> str:
    """Hash of the kblint engine's own source files — any rule or engine
    change invalidates every cached entry."""
    h = hashlib.sha256()
    here = os.path.dirname(os.path.abspath(__file__))
    for name in _ENGINE_SOURCES:
        try:
            with open(os.path.join(here, name), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"missing:" + name.encode())
    return h.hexdigest()[:16]


def content_key(relpath: str, src: str) -> str:
    """Key of (path, content): the rules scope by path and the deep
    summaries bake the module name in, so identical bytes at two paths
    (every empty __init__.py) must NOT share an entry."""
    h = hashlib.sha256()
    h.update(relpath.replace("\\", "/").encode())
    h.update(b"\0")
    h.update(src.encode("utf-8", "replace"))
    return h.hexdigest()[:24]


@dataclasses.dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    writes: int = 0


class LintCache:
    """get/put of per-file results keyed by (engine, content)."""

    def __init__(self, cache_dir: str) -> None:
        self.dir = cache_dir
        self.engine = engine_key()
        self.stats = CacheStats()
        self._gc_done = False

    @classmethod
    def from_env(cls, root: str) -> "LintCache | None":
        if os.environ.get("KBLINT_CACHE", "1") in ("0", "off", "no"):
            return None
        cache_dir = os.environ.get("KBLINT_CACHE_DIR") or os.path.join(
            root, ".kblint_cache")
        return cls(cache_dir)

    def _path(self, relpath: str, src: str) -> str:
        return os.path.join(
            self.dir, f"{self.engine}-{content_key(relpath, src)}.json")

    def get(self, relpath: str, src: str) -> dict | None:
        try:
            with open(self._path(relpath, src), encoding="utf-8") as f:
                entry = json.load(f)
        except (OSError, ValueError):
            self.stats.misses += 1
            return None
        self.stats.hits += 1
        return entry

    def put(self, relpath: str, src: str, entry: dict) -> None:
        try:
            os.makedirs(self.dir, exist_ok=True)
            self._gc_stale()
            tmp = self._path(relpath, src) + f".tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(entry, f, separators=(",", ":"))
            os.replace(tmp, self._path(relpath, src))
            self.stats.writes += 1
        except OSError:
            pass  # a read-only tree degrades to uncached, never to failure

    def _gc_stale(self) -> None:
        """Drop entries written by a different engine version (rules.py
        edits would otherwise accrete dead cache files forever)."""
        if self._gc_done:
            return
        self._gc_done = True
        try:
            for name in os.listdir(self.dir):
                stale_entry = (name.endswith(".json")
                               and not name.startswith(self.engine))
                # a killed writer leaves .json.tmp.<pid> orphans behind
                orphan_tmp = ".json.tmp." in name
                if stale_entry or orphan_tmp:
                    try:
                        os.unlink(os.path.join(self.dir, name))
                    except OSError:
                        pass
        except OSError:
            pass
