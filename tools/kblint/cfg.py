"""Per-function control-flow graphs with explicit exception edges, and the
must-reach typestate rules KB123–KB126 (linear-resource leak detection).

kblint's first three tiers check *where* code runs (locks, threads,
tracing); this tier checks *whether an acquired resource is released on
every path the runtime can actually take* — including the paths PR 11's
fault plane made routine, where any storage call raises mid-flight.

Three layers:

1. **CFG construction** (:func:`build_cfg`) — per-function graphs lowered
   straight from the AST with the edges that matter for leaks made
   explicit: every statement containing a call gets an exception edge to
   the innermost handler (or the function's exceptional exit), ``finally``
   bodies are duplicated per outgoing edge kind (normal / exception /
   return / break / continue) so a release in a ``finally`` covers all of
   them, ``return``/``break``/``continue`` route through enclosing
   ``finally`` blocks, and ``while True`` heads get no phantom fall-
   through edge (the dispatcher-loop shape must not fabricate an exit).

2. **Obligations** — acquire sites per rule, with a flow-insensitive
   alias closure (containers absorb: ``p["rev"] = rev`` makes ``p`` —
   and, through ``for p in pending``, ``pending`` — carry the dealt
   revision's obligation) and per-rule discharge/transfer policies
   (RacerD-ownership style: returning the resource, storing it on
   ``self``, or passing it to a callee that provably discharges it
   transfers the obligation; passing it to a call the resolver cannot
   see is an OPTIMISTIC transfer, counted in
   ``stats["leak_unresolved_transfers"]`` — the same honest-blindness
   contract as KB112).

3. **Must-reach dataflow** — BFS from each acquire site over the CFG,
   stopping at discharge nodes; a reachable exit means a leaking path,
   and the BFS parent chain is the reported witness (acquire site →
   escaping edge). KB123/KB126 demand discharge on ALL paths; KB124/KB125
   flag only paths that traverse an exception edge (a normal-path
   non-release is the sanctioned handoff protocol — the scheduler
   dispatcher hands its slot to the worker with the queued request).

The rules:

- **KB123** dealt-revision leak: every ``TSO.deal``/``deal_block`` result
  must reach ``_notify``/``_notify_many`` (valid, failed or uncertain
  notify — the sequencer needs ALL of them) on every path, or have its
  ownership transferred. A dealt revision that never reaches the
  sequencer wedges the revision stream forever (the etcd revision-gap
  contract).
- **KB124** manual lock acquire (``.acquire()`` outside ``with``, or the
  scheduler's ``_acquire_slot``/``_release_slot`` protocol pair) not
  released on an exception edge.
- **KB125** registration leak: watcher-hub registration, trace-span open,
  callback-gauge registration, fault-plane arming that an exception edge
  can escape without the matching deregistration.
- **KB126** stream/channel/handle lifecycle: gRPC channels, sockets and
  file handles must be closed on all paths or provably transferred.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Any, Iterable

from .core import Finding
from .graph import ProjectGraph, module_name_for
from .rules import dotted_name, terminal_name

_LOCK_NAME_RE = re.compile(r"lock$", re.IGNORECASE)

#: manual-lock constructors for the KB124 prescan. Semaphores are
#: deliberately absent: this codebase uses them as wakeup *kicks*
#: (``_rebuild_kick.acquire(blocking=False)`` consumes a signal token —
#: releasing it on exit would be a bug, not a fix).
_MANUAL_LOCK_CTORS = ("threading.Lock", "threading.RLock",
                      "threading.Condition")

#: project release protocols that behave like locks without being them:
#: acquire terminal -> (release terminal, self-container handoff allowed).
#: The scheduler dispatcher hands its slot to the worker by queueing the
#: request (``self._runq.append(req)``), so a self-container append after
#: a protocol acquire transfers the obligation.
_PROTOCOL_PAIRS = {
    "_acquire_slot": ("_release_slot", True),
}

#: KB125 registration pairs: (acquire terminals, release terminals,
#: kind label, receiver-substring requirement or None). Pairs whose
#: registration returns no handle (gauges) discharge on ANY matching
#: deregistration call — there is no token to data-link.
_REG_PAIRS: list[tuple[frozenset, frozenset, str, str | None]] = [
    (frozenset({"add_watcher", "add_watcher_with_replay"}),
     frozenset({"delete_watcher"}), "watcher", None),
    (frozenset({"register_gauge_fn"}),
     frozenset({"unregister_gauge_fn"}), "gauge", None),
    (frozenset({"arm"}), frozenset({"close", "disarm"}), "fault-plane",
     "plane"),
]

#: KB123 discharge terminals: the sequencer feed. Both valid and invalid
#: notifies count — the contract is that every dealt revision reaches the
#: ring, not that it succeeds.
_NOTIFY_TERMINALS = frozenset({"_notify", "_notify_many"})

#: KB126 acquire call names (dotted) and close terminals
_HANDLE_CTORS = frozenset({
    "grpc.insecure_channel", "grpc.secure_channel", "socket.socket",
    "open",
})
_CLOSE_TERMINALS = frozenset({"close", "shutdown"})


# ------------------------------------------------------------------- CFG


class Node:
    """One CFG node ≈ one statement occurrence. ``finally`` lowering
    duplicates statements, so a source statement can own several nodes."""

    __slots__ = ("line", "label", "succ", "stmt", "branch_else")

    def __init__(self, line: int, label: str,
                 stmt: ast.stmt | None = None) -> None:
        self.line = line
        self.label = label
        self.stmt = stmt
        self.succ: list[tuple["Node", str]] = []  # (target, "step"|"exc")
        self.branch_else: "Node | None" = None    # If: the fall-through arm

    def edge(self, other: "Node", kind: str = "step") -> None:
        self.succ.append((other, kind))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Node :{self.line} {self.label}>"


@dataclasses.dataclass
class _Frame:
    """Lowering context: where each non-local edge kind goes from here."""

    exc: Node
    ret: Node
    brk: Node | None = None
    cont: Node | None = None


class CFG:
    def __init__(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        self.fn = fn
        self.exit = Node(getattr(fn, "end_lineno", fn.lineno) or fn.lineno,
                         "normal-exit")
        self.raise_exit = Node(fn.lineno, "raise-exit")
        self.stmt_nodes: dict[int, list[Node]] = {}  # id(stmt) -> nodes
        self._builder = _Builder(self)
        self.entry = self._builder.block(
            fn.body, self.exit, _Frame(exc=self.raise_exit, ret=self.exit))

    def nodes_for(self, stmt: ast.stmt) -> list[Node]:
        return self.stmt_nodes.get(id(stmt), [])


def _stmt_exprs(st: ast.stmt) -> list[ast.expr]:
    """The expressions evaluated BY this statement's own node (compound
    statements only evaluate their header here; bodies are lowered into
    their own nodes)."""
    if isinstance(st, (ast.If, ast.While)):
        return [st.test]
    if isinstance(st, ast.For):
        return [st.iter]
    if isinstance(st, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in st.items]
    if isinstance(st, ast.Try):
        return []
    out: list[ast.expr] = []
    for child in ast.iter_child_nodes(st):
        if isinstance(child, ast.expr):
            out.append(child)
    return out


#: calls modeled as non-raising: plain constructors (project record types
#: are dataclasses — a genuinely raising ``__init__`` is a documented
#: miss) and the total builtins. Without this every ``event =
#: WatchEvent(revision=rev, ...)`` between a deal and its notify-finally
#: fabricates an exception edge no runtime can take.
_NONRAISING_CALLS = frozenset({
    "enumerate", "len", "range", "zip", "sorted", "reversed", "min", "max",
    "sum", "abs", "id", "repr", "str", "int", "float", "bool", "bytes",
    "tuple", "list", "dict", "set", "frozenset", "isinstance", "hasattr",
    "getattr", "callable", "type", "format",
    # sanitizer ownership-transfer annotations (util/lockcheck.py): no-ops
    # by contract — an annotation that could raise between a try-acquire
    # and the worker spawn would itself be the leak it exists to describe
    "handoff", "adopt",
})


def _call_may_raise(call: ast.Call) -> bool:
    term = terminal_name(call.func)
    if term in _NONRAISING_CALLS:
        return False
    if term[:1].isupper():
        return False
    return True


def _can_raise(st: ast.stmt) -> bool:
    """Whether this statement's own evaluation can raise. Calls only
    (plus ``raise``/``assert``): subscripts and attribute loads can
    technically raise too, but flagging those paths would drown the
    signal — chaos injects faults through CALLS. A documented miss."""
    if isinstance(st, (ast.Raise, ast.Assert)):
        return True
    for expr in _stmt_exprs(st):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and _call_may_raise(node):
                return True
            if isinstance(node, (ast.Await, ast.Yield, ast.YieldFrom)):
                return True
    return False


def _is_const_true(expr: ast.expr) -> bool:
    return isinstance(expr, ast.Constant) and bool(expr.value) is True


def _catches_everything(handlers: list[ast.ExceptHandler]) -> bool:
    for h in handlers:
        if h.type is None:
            return True
        for name in ([dotted_name(e) for e in h.type.elts]
                     if isinstance(h.type, ast.Tuple)
                     else [dotted_name(h.type)]):
            if name.split(".")[-1] in ("Exception", "BaseException"):
                return True
    return False


class _Builder:
    def __init__(self, cfg: CFG) -> None:
        self.cfg = cfg

    def block(self, stmts: list[ast.stmt], succ: Node, frame: _Frame) -> Node:
        """Lower ``stmts`` (right-to-left so every statement knows its
        continuation); returns the entry node."""
        for st in reversed(stmts):
            succ = self.stmt(st, succ, frame)
        return succ

    def _node(self, st: ast.stmt, label: str) -> Node:
        n = Node(st.lineno, label, st)
        self.cfg.stmt_nodes.setdefault(id(st), []).append(n)
        return n

    def stmt(self, st: ast.stmt, succ: Node, frame: _Frame) -> Node:
        if isinstance(st, ast.Return):
            n = self._node(st, "return")
            n.edge(frame.ret)
            if _can_raise(st):
                n.edge(frame.exc, "exc")
            return n
        if isinstance(st, ast.Raise):
            n = self._node(st, "raise")
            n.edge(frame.exc, "exc")
            return n
        if isinstance(st, ast.Break):
            n = self._node(st, "break")
            n.edge(frame.brk if frame.brk is not None else frame.ret)
            return n
        if isinstance(st, ast.Continue):
            n = self._node(st, "continue")
            n.edge(frame.cont if frame.cont is not None else frame.ret)
            return n
        if isinstance(st, ast.If):
            n = self._node(st, "if")
            body = self.block(st.body, succ, frame)
            orelse = self.block(st.orelse, succ, frame) if st.orelse else succ
            n.edge(body)
            n.edge(orelse)
            n.branch_else = orelse
            if _can_raise(st):
                n.edge(frame.exc, "exc")
            return n
        if isinstance(st, ast.While):
            n = self._node(st, "while")
            inner = dataclasses.replace(frame, brk=succ, cont=n)
            body = self.block(st.body, n, inner)
            n.edge(body)
            if not _is_const_true(st.test):
                # `while True:` has no fall-through: fabricating one would
                # invent leak paths that skip the loop body entirely
                tail = self.block(st.orelse, succ, frame) if st.orelse else succ
                n.edge(tail)
            if _can_raise(st):
                n.edge(frame.exc, "exc")
            return n
        if isinstance(st, (ast.For, ast.AsyncFor)):
            n = self._node(st, "for")
            inner = dataclasses.replace(frame, brk=succ, cont=n)
            body = self.block(st.body, n, inner)
            n.edge(body)
            tail = self.block(st.orelse, succ, frame) if st.orelse else succ
            n.edge(tail)
            if _can_raise(st):
                n.edge(frame.exc, "exc")
            return n
        if isinstance(st, (ast.With, ast.AsyncWith)):
            # `with` guarantees __exit__ on both the normal and the
            # exception path — the desugaring that matters for leaks is
            # only that the body's exceptions still propagate outward
            n = self._node(st, "with")
            body = self.block(st.body, succ, frame)
            n.edge(body)
            if _can_raise(st):
                n.edge(frame.exc, "exc")
            return n
        if isinstance(st, ast.Try):
            return self._try(st, succ, frame)
        if isinstance(st, ast.Match):
            n = self._node(st, "match")
            for case in st.cases:
                n.edge(self.block(case.body, succ, frame))
            n.edge(succ)  # no case matched
            if _can_raise(st):
                n.edge(frame.exc, "exc")
            return n
        label = type(st).__name__.lower()
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            label = f"call {terminal_name(st.value.func) or '?'}"
        elif isinstance(st, ast.Assign) and st.targets:
            label = f"assign {terminal_name(st.targets[0]) or '...'}"
        n = self._node(st, label)
        n.edge(succ)
        if _can_raise(st):
            n.edge(frame.exc, "exc")
        return n

    def _try(self, st: ast.Try, succ: Node, frame: _Frame) -> Node:
        # finally copies, one per outgoing edge kind (classic lowering:
        # a release inside `finally` must cover normal completion AND
        # exception AND return AND break/continue)
        if st.finalbody:
            fin_norm = self.block(st.finalbody, succ, frame)
            fin_exc = self.block(st.finalbody, frame.exc, frame)
            fin_ret = self.block(st.finalbody, frame.ret, frame)
            fin_brk = (self.block(st.finalbody, frame.brk, frame)
                       if frame.brk is not None else None)
            fin_cont = (self.block(st.finalbody, frame.cont, frame)
                        if frame.cont is not None else None)
        else:
            fin_norm, fin_exc, fin_ret = succ, frame.exc, frame.ret
            fin_brk, fin_cont = frame.brk, frame.cont
        outer = _Frame(exc=fin_exc, ret=fin_ret, brk=fin_brk, cont=fin_cont)
        # handler bodies: their own exceptions go through finally outward
        handler_entries: list[Node] = []
        for h in st.handlers:
            hn = Node(h.lineno, "except")
            hn.edge(self.block(h.body, fin_norm, outer))
            handler_entries.append(hn)
        if st.handlers:
            dispatch = Node(st.lineno, "except-dispatch")
            for hn in handler_entries:
                dispatch.edge(hn)
            if not _catches_everything(st.handlers):
                # an exception no handler matches propagates out (through
                # finally); with a catch-all this edge would fabricate
                # leak paths on KeyboardInterrupt only
                dispatch.edge(fin_exc, "exc")
            body_exc: Node = dispatch
        else:
            body_exc = fin_exc
        inner = _Frame(exc=body_exc, ret=fin_ret, brk=fin_brk, cont=fin_cont)
        after_body = (self.block(st.orelse, fin_norm, outer) if st.orelse
                      else fin_norm)
        return self.block(st.body, after_body, inner)


def build_cfg(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> CFG:
    return CFG(fn)


# ------------------------------------------------------------ obligations


@dataclasses.dataclass
class Obligation:
    rule: str                 # KB123..KB126
    kind: str                 # revision | lock | slot | watcher | ...
    line: int
    col: int
    what: str                 # display name of the resource
    start_nodes: list[Node]   # where the resource provably exists
    aliases: set[str]         # names carrying the obligation ("" = none)
    recv: str = ""            # KB124: dotted receiver of .acquire()
    release_terminals: frozenset = frozenset()
    handoff_append: bool = False   # KB124 protocol: self-container handoff
    exception_only: bool = False   # KB124/KB125: flag exc-escapes only
    linked: bool = True            # discharge must mention an alias


def _names_in(expr: ast.expr | None) -> set[str]:
    if expr is None:
        return set()
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _root_name(expr: ast.expr) -> str | None:
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _alias_closure(fn: ast.AST, seeds: set[str]) -> set[str]:
    """Flow-insensitive alias/absorption closure over the function body.

    Assignments propagate target <- value; container stores absorb
    (``p["rev"] = rev`` marks ``p``); ``x.append(v)``-style mutators
    absorb into the receiver; ``for``-targets link BIDIRECTIONALLY with
    the iterated container (``for p in pending`` ties ``p`` and
    ``pending`` — the write-batch event list needs the backward hop).
    Optimistic by design: over-aliasing means more discharges recognized,
    i.e. fewer false positives and more (counted) false negatives."""
    aliases = set(seeds)
    _ABSORB_METHODS = {"append", "add", "put", "extend", "appendleft",
                       "put_nowait", "insert", "setdefault"}
    for _ in range(10):
        before = len(aliases)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                vnames = _names_in(node.value)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        if vnames & aliases:
                            aliases.add(tgt.id)
                    elif isinstance(tgt, (ast.Tuple, ast.List)):
                        if vnames & aliases:
                            aliases |= {e.id for e in tgt.elts
                                        if isinstance(e, ast.Name)}
                    elif isinstance(tgt, ast.Subscript):
                        root = _root_name(tgt)
                        if root and vnames & aliases:
                            aliases.add(root)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                tnames = _names_in(node.target)
                inames = _names_in(node.iter)
                if tnames & aliases:
                    aliases |= inames
                if inames & aliases:
                    aliases |= tnames
            elif isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _ABSORB_METHODS):
                    argn: set[str] = set()
                    for a in node.args:
                        argn |= _names_in(a)
                    if argn & aliases:
                        root = _root_name(node.func.value)
                        if root:
                            aliases.add(root)
        if len(aliases) == before:
            break
    return aliases


# --------------------------------------------------------- leak analysis


class _FileContext:
    """Per-file helpers shared by every function analysis: lock-ish
    attribute prescan (KB124) and the call-resolution index from the
    ProjectGraph (transfer policies)."""

    def __init__(self, relpath: str, tree: ast.Module,
                 graph: ProjectGraph | None) -> None:
        self.relpath = relpath
        self.module = module_name_for(relpath)
        self.graph = graph
        self.lockish_attrs: dict[str, set[str]] = {}  # class -> attrs
        self.lockish_globals: set[str] = set()
        #: class -> every call terminal in its body, for the class-lifecycle
        #: transfer: a HANDLE-LESS registration (gauge, fault-plane) can
        #: only ever be cleaned up by the instance's own teardown, so a
        #: matching deregistration ANYWHERE in the class transfers the
        #: obligation to the instance lifecycle. A class that registers but
        #: never deregisters is the real leak (its instances can never be
        #: cleanly dropped) — that still fires.
        self.class_call_terminals: dict[str, set[str]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                terms: set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        t = terminal_name(sub.func)
                        if t:
                            terms.add(t)
                self.class_call_terminals[node.name] = terms
                attrs: set[str] = set()
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Assign)
                            and isinstance(sub.value, ast.Call)
                            and dotted_name(sub.value.func)
                            in _MANUAL_LOCK_CTORS):
                        for tgt in sub.targets:
                            if (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                attrs.add(tgt.attr)
                self.lockish_attrs[node.name] = attrs
            elif (isinstance(node, ast.Assign)
                  and isinstance(node.value, ast.Call)
                  and dotted_name(node.value.func) in _MANUAL_LOCK_CTORS):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.lockish_globals.add(tgt.id)

    def is_lockish(self, recv: str, cls: str | None) -> bool:
        if not recv:
            return False
        tail = recv.split(".")[-1]
        if _LOCK_NAME_RE.search(tail):
            return True
        if recv.startswith("self.") and cls:
            return tail in self.lockish_attrs.get(cls, ())
        return tail in self.lockish_globals

    def resolution(self, qn: str) -> dict[int, tuple[bool, bool]]:
        """line -> (any resolved target, any unresolved project call).
        Drives the per-rule transfer policies; functions the graph does
        not know (nested defs under a different qualname spelling) read
        as fully unresolved — optimistic transfer, counted."""
        out: dict[int, tuple[bool, bool]] = {}
        if self.graph is None:
            return out
        for cs, targets in self.graph.calls.get(qn, ()):
            if cs.is_ref:
                continue
            res, unres = out.get(cs.line, (False, False))
            if targets:
                res = True
            elif self.graph._counts_as_unresolved(cs.name):
                unres = True
            out[cs.line] = (res, unres)
        return out

    def resolved_targets(self, qn: str, line: int) -> list[str]:
        if self.graph is None:
            return []
        hits: list[str] = []
        for cs, targets in self.graph.calls.get(qn, ()):
            if not cs.is_ref and cs.line == line:
                hits.extend(targets)
        return hits


def _notify_reach(graph: ProjectGraph) -> set[str]:
    """Functions that (transitively, over resolved call edges) feed the
    sequencer: passing a dealt revision into one of these transfers the
    KB123 obligation — the callee owns delivery now."""
    seeds = set()
    for qn, fs in graph.functions.items():
        for cs in fs.calls:
            if not cs.is_ref and cs.name.split(".")[-1] in _NOTIFY_TERMINALS:
                seeds.add(qn)
                break
    out = set(seeds)
    frontier = list(seeds)
    while frontier:
        qn = frontier.pop()
        for caller in graph.callers.get(qn, ()):
            if caller not in out:
                out.add(caller)
                frontier.append(caller)
    return out


class _FuncLeaks:
    """Obligations + must-reach for one function."""

    def __init__(self, fn: ast.FunctionDef | ast.AsyncFunctionDef,
                 qn: str, cls: str | None, ctx: _FileContext,
                 notify_reach: set[str], stats: dict[str, int]) -> None:
        self.fn = fn
        self.qn = qn
        self.cls = cls
        self.ctx = ctx
        self.notify_reach = notify_reach
        self.stats = stats
        self.cfg: CFG | None = None
        self.obligations: list[Obligation] = []
        self._with_ctx_calls: set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call):
                            self._with_ctx_calls.add(id(sub))

    # -- acquire-site discovery -------------------------------------------
    def find_obligations(self) -> None:
        body_stmts = [st for st in ast.walk(self.fn)
                      if isinstance(st, ast.stmt)]
        for st in body_stmts:
            self._scan_stmt(st)

    def _ensure_cfg(self) -> CFG:
        if self.cfg is None:
            self.cfg = build_cfg(self.fn)
        return self.cfg

    def _start_after(self, st: ast.stmt) -> list[Node]:
        """Normal-completion successors of st's nodes: the obligation
        exists only once the acquire call returned."""
        cfg = self._ensure_cfg()
        out: list[Node] = []
        for n in cfg.nodes_for(st):
            out.extend(t for t, kind in n.succ if kind == "step")
        return out

    def _guard_start(self, st: ast.If, call: ast.Call,
                     positive_in_body: bool) -> list[Node] | None:
        """`if not lk.acquire(...): <no-fallthrough>` — the obligation
        begins at the fall-through arm. Returns None when the guard shape
        is too complex to place (counted, not guessed)."""
        cfg = self._ensure_cfg()
        out: list[Node] = []
        for n in cfg.nodes_for(st):
            if positive_in_body:
                # `if lk.acquire():` — acquired inside the body arm
                arms = [t for t, kind in n.succ
                        if kind == "step" and t is not n.branch_else]
                out.extend(arms)
            elif n.branch_else is not None:
                out.append(n.branch_else)
        return out or None

    def _scan_stmt(self, st: ast.stmt) -> None:
        for call in self._calls_of(st):
            name = dotted_name(call.func)
            term = terminal_name(call.func)
            if id(call) in self._with_ctx_calls:
                continue  # `with` discharges by construction
            if not isinstance(st, ast.Return):
                # `return self.tso.deal()` / `return open(p)`: the fresh
                # resource is handed straight to the caller — caller-side
                # accounting (the return-alias transfer, one level up)
                # owns it. KB124 still applies: its resource is the
                # acquire's side effect, not the returned value.
                self._match_kb123(st, call, name, term)
                self._match_kb125(st, call, name, term)
                self._match_kb126(st, call, name, term)
            self._match_kb124(st, call, name, term)

    def _calls_of(self, st: ast.stmt) -> list[ast.Call]:
        return [n for e in _stmt_exprs(st) for n in ast.walk(e)
                if isinstance(n, ast.Call)]

    def _bound_names(self, st: ast.stmt, call: ast.Call) -> set[str]:
        """Names the call's result lands in, when st is `x = call(...)`
        or `x, y = call(...)`."""
        if isinstance(st, ast.Assign) and st.value is call:
            names: set[str] = set()
            for tgt in st.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    names |= {e.id for e in tgt.elts
                              if isinstance(e, ast.Name)}
            return names
        return set()

    def _add(self, ob: Obligation) -> None:
        self.obligations.append(ob)
        self.stats["leak_acquires"] = self.stats.get("leak_acquires", 0) + 1
        key = f"{ob.rule.lower()}_sites"
        self.stats[key] = self.stats.get(key, 0) + 1

    # -- per-rule acquire matchers ----------------------------------------
    def _match_kb123(self, st: ast.stmt, call: ast.Call, name: str,
                     term: str) -> None:
        if term not in ("deal", "deal_block"):
            return
        recv = name[: -len(term) - 1] if name.endswith("." + term) else ""
        if "tso" not in recv.lower():
            return
        bound = self._bound_names(st, call)
        if not bound:
            # a bare `self.tso.deal()` discarding the revision is itself a
            # leak — but the tree never does it; treat as linked-to-nothing
            bound = set()
        self._add(Obligation(
            rule="KB123", kind="revision", line=call.lineno,
            col=call.col_offset,
            what=f"dealt revision {'/'.join(sorted(bound)) or '(unbound)'}"
                 f" ({name}())",
            start_nodes=self._start_after(st),
            aliases=_alias_closure(self.fn, bound) if bound else set(),
            release_terminals=_NOTIFY_TERMINALS, linked=bool(bound)))

    def _match_kb124(self, st: ast.stmt, call: ast.Call, name: str,
                     term: str) -> None:
        handoff = False
        if term == "acquire":
            recv = name[: -len(term) - 1] if name.endswith(".acquire") else ""
            if not self.ctx.is_lockish(recv, self.cls):
                return
            release = frozenset({"release"})
        elif term in _PROTOCOL_PAIRS:
            recv = name[: -len(term) - 1] if "." in name else ""
            rel, handoff = _PROTOCOL_PAIRS[term]
            release = frozenset({rel})
        else:
            return
        start = self._conditional_start(st, call)
        if start is None:
            self.stats["leak_skipped_conditional"] = self.stats.get(
                "leak_skipped_conditional", 0) + 1
            return
        self._add(Obligation(
            rule="KB124", kind="lock" if term == "acquire" else "slot",
            line=call.lineno, col=call.col_offset,
            what=f"{name}()", start_nodes=start, aliases=set(), recv=recv,
            release_terminals=release, handoff_append=handoff,
            exception_only=True, linked=False))

    def _conditional_start(self, st: ast.stmt,
                           call: ast.Call) -> list[Node] | None:
        """Where a maybe-failing acquire's obligation begins. Handles the
        guard idioms; anything gnarlier is skipped and counted."""
        if isinstance(st, ast.If):
            test = st.test
            if (isinstance(test, ast.UnaryOp)
                    and isinstance(test.op, ast.Not) and test.operand is call):
                return self._guard_start(st, call, positive_in_body=False)
            if test is call:
                return self._guard_start(st, call, positive_in_body=True)
            return None  # acquire buried in a compound condition
        if isinstance(st, (ast.While,)):
            return None
        return self._start_after(st)

    def _match_kb125(self, st: ast.stmt, call: ast.Call, name: str,
                     term: str) -> None:
        for acq_terms, rel_terms, kind, recv_req in _REG_PAIRS:
            if term not in acq_terms:
                continue
            recv = name[: -len(term) - 1] if "." in name else ""
            if recv_req is not None and recv_req not in recv.lower():
                return
            bound = self._bound_names(st, call)
            if not bound and self.cls is not None and (
                    rel_terms
                    & self.ctx.class_call_terminals.get(self.cls, set())):
                # handle-less registration in a class that owns a matching
                # deregistration path: instance-lifecycle transfer
                self.stats["kb125_class_transfers"] = self.stats.get(
                    "kb125_class_transfers", 0) + 1
                return
            self._add(Obligation(
                rule="KB125", kind=kind, line=call.lineno,
                col=call.col_offset, what=f"{name}()",
                start_nodes=self._start_after(st),
                aliases=_alias_closure(self.fn, bound) if bound else set(),
                release_terminals=rel_terms, exception_only=True,
                linked=bool(bound)))
            return
        # trace spans constructed directly (the Tracer.span CM is the
        # sanctioned shape and discharges in its finally)
        if term == "Span" and not name[:1].islower():
            bound = self._bound_names(st, call)
            if not bound:
                return
            self._add(Obligation(
                rule="KB125", kind="span", line=call.lineno,
                col=call.col_offset, what=f"span {'/'.join(sorted(bound))}",
                start_nodes=self._start_after(st),
                aliases=_alias_closure(self.fn, bound),
                release_terminals=frozenset({"finish"}),
                exception_only=True, linked=True))

    def _match_kb126(self, st: ast.stmt, call: ast.Call, name: str,
                     term: str) -> None:
        if name not in _HANDLE_CTORS:
            return
        bound = self._bound_names(st, call)
        if not bound:
            # direct self-store (`self._ch = grpc.insecure_channel(t)`) is
            # an ownership transfer to the instance; chained immediate use
            # without binding is not trackable — skip, don't guess
            return
        self._add(Obligation(
            rule="KB126", kind="handle", line=call.lineno,
            col=call.col_offset,
            what=f"{name}() handle {'/'.join(sorted(bound))}",
            start_nodes=self._start_after(st),
            aliases=_alias_closure(self.fn, bound),
            release_terminals=_CLOSE_TERMINALS, linked=True))

    # -- discharge classification -----------------------------------------
    def _discharges(self, ob: Obligation, node: Node) -> tuple[bool, bool]:
        """(discharges, used_unresolved_transfer) for one CFG node."""
        st = node.stmt
        if st is None:
            return False, False
        for expr in _stmt_exprs(st):
            for sub in ast.walk(expr):
                if not isinstance(sub, ast.Call):
                    continue
                got = self._call_discharges(ob, st, sub)
                if got[0]:
                    return got
        # guard-correlated release: `if fh is not None: fh.close()` — the
        # test re-checks exactly the condition under which the resource was
        # acquired, so both arms are accounted for (None arm has nothing to
        # release). Without this, path-insensitivity walks the skip arm
        # with the obligation still live.
        if (ob.linked and ob.aliases and isinstance(st, ast.If)
                and _names_in(st.test) & ob.aliases):
            for sub in ast.walk(st):
                if (isinstance(sub, ast.Call)
                        and terminal_name(sub.func)
                        in ob.release_terminals):
                    args: set[str] = set()
                    for a in (*sub.args, *(kw.value for kw in sub.keywords)):
                        args |= _names_in(a)
                    root = (_root_name(sub.func)
                            if isinstance(sub.func, ast.Attribute) else None)
                    if root in ob.aliases or args & ob.aliases:
                        return True, False
        # return <alias> / self.x = <alias>: ownership transfer
        if ob.linked and ob.aliases:
            if (isinstance(st, ast.Return)
                    and _names_in(st.value) & ob.aliases):
                return True, False
            if isinstance(st, ast.Assign):
                if _names_in(st.value) & ob.aliases:
                    for tgt in st.targets:
                        root = (_root_name(tgt)
                                if isinstance(tgt, (ast.Attribute,
                                                    ast.Subscript))
                                else None)
                        if root in ("self", "cls"):
                            return True, False
        return False, False

    def _call_discharges(self, ob: Obligation, st: ast.stmt,
                         call: ast.Call) -> tuple[bool, bool]:
        name = dotted_name(call.func)
        term = terminal_name(call.func)
        arg_names: set[str] = set()
        for a in (*call.args, *(kw.value for kw in call.keywords)):
            arg_names |= _names_in(a)
        recv = name[: -len(term) - 1] if (name and "." in name) else ""
        # the designated release call
        if term in ob.release_terminals:
            if not ob.linked:
                # lock/slot/gauge protocols: match by receiver when one is
                # known ('self._mlock.release()' does not release _cv)
                if ob.recv and recv and recv != ob.recv:
                    return False, False
                return True, False
            if arg_names & ob.aliases or _root_name(call.func) and (
                    {_root_name(call.func)} & ob.aliases):
                return True, False
            if ob.rule == "KB123" and not ob.aliases:
                return True, False
        # KB124 handoff: queueing work into a self-container transfers the
        # slot to whoever drains the queue
        if (ob.handoff_append and term == "append"
                and isinstance(call.func, ast.Attribute)
                and _root_name(call.func) in ("self", "cls")):
            return True, False
        # ownership transfer by argument-passing
        if ob.linked and ob.aliases and arg_names & ob.aliases:
            if ob.rule == "KB126":
                # handles: any consumer owns the close (Popen(stderr=fh),
                # contextlib.closing(ch), Stub(channel))
                res, unres = self._line_resolution(call.lineno)
                if unres and not res:
                    self.stats["leak_unresolved_transfers"] = (
                        self.stats.get("leak_unresolved_transfers", 0) + 1)
                return True, False
            if ob.rule == "KB123":
                if term[:1].isupper():
                    # constructors (WatchEvent(revision=rev, ...)) record
                    # the revision; they never deliver it to the sequencer
                    return False, False
                targets = self.ctx.resolved_targets(self.qn, call.lineno)
                if targets and any(t in self.notify_reach for t in targets):
                    self.stats["leak_resolved_transfers"] = (
                        self.stats.get("leak_resolved_transfers", 0) + 1)
                    return True, False
                if not targets:
                    res, unres = self._line_resolution(call.lineno)
                    if unres:
                        # a call the resolver cannot see takes the dealt
                        # revision: optimistic transfer, counted blindness
                        self.stats["leak_unresolved_transfers"] = (
                            self.stats.get("leak_unresolved_transfers", 0)
                            + 1)
                        return True, False
            if ob.rule == "KB125" and ob.kind == "watcher":
                # wid handed to another component (reply message, registry)
                return True, False
        return False, False

    def _line_resolution(self, line: int) -> tuple[bool, bool]:
        return self.ctx.resolution(self.qn).get(line, (False, False))

    # -- must-reach -------------------------------------------------------
    def check(self) -> Iterable[Finding]:
        for ob in self.obligations:
            leak = self._must_reach(ob)
            if leak is not None:
                yield self._render(ob, leak)

    def _must_reach(self, ob: Obligation
                    ) -> tuple[list[Node], bool] | None:
        """BFS from the obligation's start nodes, stopping at discharges;
        returns (witness path, via_exception) for the first escaping path,
        or None when every path discharges."""
        cfg = self._ensure_cfg()
        assert cfg is not None
        seen: set[tuple[int, bool]] = set()
        queue: list[tuple[Node, bool, tuple[Node, ...]]] = []
        for start in ob.start_nodes:
            queue.append((start, False, (start,)))
        while queue:
            node, saw_exc, path = queue.pop(0)
            key = (id(node), saw_exc)
            if key in seen:
                continue
            seen.add(key)
            if node is cfg.exit or node is cfg.raise_exit:
                escaped_exc = saw_exc or node is cfg.raise_exit
                if ob.exception_only and not escaped_exc:
                    continue  # normal-path handoff is the protocol
                return list(path), escaped_exc
            discharged, _ = self._discharges(ob, node)
            if discharged:
                continue
            for nxt, kind in node.succ:
                queue.append((nxt, saw_exc or kind == "exc",
                              path + (nxt,)))
        return None

    def _render(self, ob: Obligation,
                leak: tuple[list[Node], bool]) -> Finding:
        path, via_exc = leak
        hops: list[str] = []
        last_line = None
        for n in path:
            if n.line != last_line and n.label not in ("except-dispatch",):
                hops.append(f"{n.label} at line {n.line}")
                last_line = n.line
        shown = hops if len(hops) <= 5 else hops[:3] + ["..."] + hops[-1:]
        how = "an exception edge" if via_exc else "a normal path"
        rel = "/".join(sorted(ob.release_terminals)) or "release"
        return Finding(
            self.ctx.relpath, ob.line, ob.col, ob.rule,
            f"{ob.what} acquired in {self.qn.rsplit('::', 1)[-1]} can "
            f"escape via {how} without reaching {rel} (witness: "
            + " -> ".join(shown) + ")")


# ------------------------------------------------------------------ driver


def _functions_with_context(tree: ast.Module, module: str
                            ) -> list[tuple[ast.AST, str, str | None]]:
    """(fn node, qualname, class) for module-level functions and methods —
    the same qualname spelling the extractor uses, so graph lookups line
    up. Nested defs are analyzed under their host's <locals> spelling."""
    out: list[tuple[ast.AST, str, str | None]] = []

    def visit(body: list[ast.stmt], cls: str | None, prefix: str) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = (f"{module}::{cls}.{node.name}" if cls
                      else f"{module}::{prefix}{node.name}")
                out.append((node, qn, cls))
                nested_prefix = (f"{cls}.{node.name}.<locals>." if cls
                                 else f"{prefix}{node.name}.<locals>.")
                for sub in ast.walk(node):
                    if (isinstance(sub, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                            and sub is not node):
                        out.append((sub,
                                    f"{module}::{nested_prefix}{sub.name}",
                                    cls))
            elif isinstance(node, ast.ClassDef) and cls is None:
                visit(node.body, node.name, "")
            elif isinstance(node, (ast.If, ast.Try)):
                for sub_body in ([node.body]
                                 + [h.body for h in getattr(node, "handlers",
                                                            [])]
                                 + [getattr(node, "orelse", [])]
                                 + [getattr(node, "finalbody", [])]):
                    visit(sub_body, cls, prefix)

    visit(tree.body, None, "")
    return out


#: quick textual triggers: files with none of these cannot host an acquire
_TRIGGERS = (".deal", ".acquire(", "_acquire_slot", "add_watcher",
             "register_gauge_fn", "insecure_channel", "secure_channel",
             "socket.socket", "= open(", "Span(", ".arm(")


def analyze_leaks(graph: ProjectGraph, sources: dict[str, str]
                  ) -> tuple[list[Finding], dict[str, int], dict[str, Any]]:
    """Run KB123–KB126 over ``sources`` ({relpath: src}, the deep-tier
    file set). Findings are scoped to kubebrain_tpu/ like the other deep
    rules. Returns (findings, stats, static leak report)."""
    stats: dict[str, int] = {}
    findings: list[Finding] = []
    sites: list[dict[str, Any]] = []
    reach = _notify_reach(graph)
    for relpath in sorted(sources):
        rp = relpath.replace("\\", "/")
        if not rp.startswith("kubebrain_tpu/"):
            continue
        src = sources[relpath]
        if not any(t in src for t in _TRIGGERS):
            continue
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        ctx = _FileContext(rp, tree, graph)
        for fn, qn, cls in _functions_with_context(tree, ctx.module):
            fl = _FuncLeaks(fn, qn, cls, ctx, reach, stats)
            fl.find_obligations()
            if not fl.obligations:
                continue
            fn_findings = list(fl.check())
            findings.extend(fn_findings)
            flagged = {(f.line, f.rule_id) for f in fn_findings}
            for ob in fl.obligations:
                sites.append({
                    "rule": ob.rule, "kind": ob.kind,
                    "path": rp, "line": ob.line,
                    "what": ob.what,
                    "leaks": (ob.line, ob.rule) in flagged,
                })
    report: dict[str, Any] = {
        "sites": sites,
        "site_count": len(sites),
        "by_kind": {},
    }
    for s in sites:
        k = report["by_kind"].setdefault(
            s["kind"], {"sites": 0, "leaking": 0})
        k["sites"] += 1
        k["leaking"] += 1 if s["leaks"] else 0
    return findings, stats, report


def leak_report(static_report: dict[str, Any],
                runtime_obs: list[dict] | None) -> dict[str, Any]:
    """The static↔runtime coverage cross-check (mirrors the KB115 and
    fieldcheck reports): which obligation kinds the static tier tracks,
    which the runtime sanitizer actually exercised, and whether the
    runtime balance closed."""
    out = dict(static_report)
    if runtime_obs is None:
        return out
    observed = {o["kind"]: o for o in runtime_obs if "kind" in o}
    static_kinds = set(out.get("by_kind", {}))
    runtime_kinds = set(observed)
    unbalanced = sorted(
        k for k, o in observed.items()
        if o.get("outstanding", 0) or o.get("violations", 0))
    matched = static_kinds & runtime_kinds
    out.update({
        "observed_kinds": {k: {kk: vv for kk, vv in o.items()
                               if kk != "kind"}
                           for k, o in sorted(observed.items())},
        "static_only_kinds": sorted(static_kinds - runtime_kinds),
        "runtime_only_kinds": sorted(runtime_kinds - static_kinds),
        "unbalanced_kinds": unbalanced,
        "coverage": (len(matched) / len(static_kinds)
                     if static_kinds else 1.0),
    })
    return out
