"""Execution-context propagation + the interprocedural rules KB112–KB122.

Contexts propagated along the :class:`~tools.kblint.graph.ProjectGraph`:

- **blocking reachability** — can this function (transitively) execute a
  call that blocks the thread? (KB112: such a call reachable from inside
  a ``with <lock>:`` region is the static twin of util/lockcheck.py's
  runtime sleep-under-lock detector.)
- **jit/shard_map tracing** — is this function's body executed under JAX
  tracing, directly (decorator) or because a traced function calls it /
  wraps a reference to it? (KB113: host sync reachable from traced code.)
- **device-array taint** — which values are device arrays, across
  aliases, returns, and parameter passing? (KB114: a taint-carrying value
  host-converted outside the KB111 materialization allowlist — the
  alias/wrapper laundering a name-based rule misses by design.)
- **async-event-loop** — reachable from a coroutine body without an
  executor hop (reported in stats; KB101 stays the lexical tier).
- **lock-acquisition order** — the static lock-order graph (KB115),
  cycle-checked and cross-checked against lockcheck's runtime-observed
  edges so the runtime detector's coverage gap becomes a number.

Every propagation is an over-approximation ON RESOLVED EDGES ONLY: calls
the resolver cannot see (``stats.unresolved_calls``) are accounted, not
guessed, so a clean report means "clean modulo N blind spots", and N is
printed next to the verdict.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable

from .core import Finding
from .rules import _BLOCKING_CALLS, _BLOCKING_MODULES, _HOST_TRANSFER_ALLOWED
from .graph import (_CALLBACK_SINKS, _LOCK_NAME_RE, _TRACE_WRAPPERS,
                    AttrAccess, CallSite, FunctionSummary, ProjectGraph)

#: rules implemented on the interprocedural engine
DEEP_RULES = {
    "KB112": "blocking call transitively reachable while a lock is held",
    "KB113": "host sync transitively reachable from jit/shard_map-traced code",
    "KB114": "device-array taint escaping to host outside the KB111 allowlist",
    "KB115": "static lock-acquisition-order graph must be acyclic",
    "KB119": "leader-only mutation surface reachable from follower-role "
             "(kubebrain_tpu/replica/) serving modules",
    "KB120": "field written under a lock at one site but accessed from a "
             "thread-escaping context with no common lock at another",
    "KB121": "field guarded by DIFFERENT locks at different sites (guard "
             "inconsistency)",
    "KB122": "lexical check-then-act: guarded read whose dependent write "
             "re-acquires the lock (released across the decision)",
    "KB123": "dealt revision can escape without reaching the sequencer "
             "(_notify/_notify_many) on some path",
    "KB124": "manually acquired lock/slot not released on an exception edge",
    "KB125": "registration (watcher/gauge/span/fault-plane) leaked on an "
             "exception edge without the matching deregistration",
    "KB126": "stream/channel/handle not closed on all paths and not "
             "provably ownership-transferred",
}

#: sync op kinds that are a host sync in ANY traced context, regardless of
#: operand taint (they have no legitimate traced use)
_ALWAYS_SYNC_OPS = {"block_until_ready", "device_get", "item"}


def _blocking_name(name: str) -> str | None:
    if name in _BLOCKING_CALLS:
        return name
    root = name.split(".", 1)[0]
    if root in _BLOCKING_MODULES:
        return name
    return None


@dataclasses.dataclass
class DeepResult:
    findings: list[Finding]
    stats: dict[str, Any]
    lock_graph: dict[str, Any]
    field_guards: dict[str, Any] = dataclasses.field(default_factory=dict)
    leaks: dict[str, Any] = dataclasses.field(default_factory=dict)


def _fn_label(qn: str) -> str:
    """pkg.mod::Class.meth -> Class.meth (short display form)."""
    return qn.rsplit("::", 1)[-1]


def _chain_str(chain: list[str]) -> str:
    return " -> ".join(_fn_label(q) for q in chain)


# ---------------------------------------------------------------- blocking


def _blocking_witness(graph: ProjectGraph) -> dict[str, tuple[list[str], str]]:
    """fn qualname -> (call chain ending at the blocking fn, detail).
    BFS from directly-blocking functions up the reverse call graph so the
    recorded chain is a shortest witness."""
    witness: dict[str, tuple[list[str], str]] = {}
    frontier: list[str] = []
    for qn, fs in graph.functions.items():
        for cs in fs.calls:
            if cs.is_ref:
                continue
            b = _blocking_name(cs.name)
            if b:
                witness[qn] = ([qn], f"{b}() at {fs.relpath}:{cs.line}")
                frontier.append(qn)
                break
        else:
            for op in fs.sync_ops:
                if op.op == "block_until_ready":
                    witness[qn] = ([qn], f"block_until_ready() at "
                                         f"{fs.relpath}:{op.line}")
                    frontier.append(qn)
                    break
    while frontier:
        nxt: list[str] = []
        for qn in frontier:
            chain, detail = witness[qn]
            for caller in graph.callers.get(qn, ()):
                if caller in witness:
                    continue
                # only real calls propagate; a bare reference passed around
                # executes later, in a context this edge does not witness
                for cs, targets in graph.calls.get(caller, ()):
                    if not cs.is_ref and qn in targets:
                        witness[caller] = ([caller] + chain, detail)
                        nxt.append(caller)
                        break
        frontier = nxt
    return witness


def _kb112(graph: ProjectGraph,
           blocking: dict[str, tuple[list[str], str]]) -> Iterable[Finding]:
    """A call made while lexically holding a lock, whose (transitive)
    callee reaches a blocking call. Direct blocking-under-lock stays
    KB102's lexical finding; KB112 is the multi-hop twin."""
    for qn, fs in graph.functions.items():
        if not fs.relpath.replace("\\", "/").startswith("kubebrain_tpu/"):
            continue
        for cs, targets in graph.calls.get(qn, ()):
            if cs.is_ref or not cs.under_locks:
                continue
            for tgt in targets:
                w = blocking.get(tgt)
                if w is None:
                    continue
                chain, detail = w
                held = cs.under_locks[-1]
                yield Finding(
                    fs.relpath, cs.line, cs.col, "KB112",
                    f"blocking call reachable while holding {held}: "
                    f"{_fn_label(qn)} -> {_chain_str(chain)} reaches {detail}")
                break  # one finding per call site


# ------------------------------------------------------------------ traced


def _trace_forwarders(graph: ProjectGraph) -> set[str]:
    """Project functions that forward one of their OWN parameters into a
    trace wrapper (``def _maybe_shard_map(f, ...): return shard_map(f,
    ...)``): a reference passed into one of these enters tracing just as
    surely as one passed to ``jax.jit`` directly. Transitive — a
    forwarder's forwarder forwards."""
    fwd: set[str] = set()
    changed = True
    while changed:
        changed = False
        for qn, fs in graph.functions.items():
            if qn in fwd:
                continue
            resolved = graph.calls.get(qn, ())
            for cs in fs.calls:
                if not cs.is_ref or cs.name not in fs.params:
                    continue
                hit = cs.ref_of in _TRACE_WRAPPERS
                if not hit:
                    # the wrapping call may itself resolve to a forwarder
                    for cs2, targets in resolved:
                        if (not cs2.is_ref and cs2.name == cs.ref_of
                                and set(targets) & fwd):
                            hit = True
                            break
                if hit:
                    fwd.add(qn)
                    changed = True
                    break
    return fwd


def _traced_set(graph: ProjectGraph) -> dict[str, list[str]]:
    """fn qualname -> witness chain from a jit/shard_map entry. Entries are
    decorator-marked functions plus references passed into a trace wrapper
    (``jax.jit(f)``, ``shard_map(f, ...)``, ``pl.pallas_call(body)(...)``)
    — directly OR through a project forwarder like ``_maybe_shard_map``.
    Inside a traced function both calls AND bare references propagate —
    the ``_maybe_shard_map(partial(kernel, ...))`` idiom wraps-and-calls."""
    forwarders = _trace_forwarders(graph)
    traced: dict[str, list[str]] = {}
    frontier: list[str] = []
    for qn, fs in graph.functions.items():
        if fs.jit_entry:
            traced[qn] = [qn]
            frontier.append(qn)
    for qn, fs in graph.functions.items():
        resolved = graph.calls.get(qn, ())
        for cs, targets in resolved:
            if not cs.is_ref:
                continue
            entering = cs.ref_of in _TRACE_WRAPPERS
            if not entering and cs.ref_of:
                for cs2, tgts2 in resolved:
                    if (not cs2.is_ref and cs2.name == cs.ref_of
                            and set(tgts2) & forwarders):
                        entering = True
                        break
            if entering:
                for tgt in targets:
                    if tgt not in traced:
                        traced[tgt] = [tgt]
                        frontier.append(tgt)
    while frontier:
        nxt: list[str] = []
        for qn in frontier:
            chain = traced[qn]
            for cs, targets in graph.calls.get(qn, ()):
                for tgt in targets:
                    if tgt in traced:
                        continue
                    traced[tgt] = chain + [tgt]
                    nxt.append(tgt)
        frontier = nxt
    return traced


def _kb113(graph: ProjectGraph, traced: dict[str, list[str]],
           taint: "_TaintSolver") -> Iterable[Finding]:
    for qn, chain in traced.items():
        fs = graph.functions[qn]
        for op in fs.sync_ops:
            flag = op.op in _ALWAYS_SYNC_OPS
            if not flag:
                # float()/np.asarray()/... only when the operand is a
                # traced value: device-tainted, or a parameter (parameters
                # of a traced function ARE tracers)
                definite, params = taint.eval_atoms(fs, op.atoms)
                flag = definite or bool(params)
            if flag:
                via = (f" (traced via {_chain_str(chain)})"
                       if len(chain) > 1 or chain[0] != qn else
                       f" (jit entry {_fn_label(qn)!r})")
                yield Finding(
                    fs.relpath, op.line, 0, "KB113",
                    f"host sync {op.op} reachable under jit/shard_map "
                    f"tracing{via}")


# ------------------------------------------------------------------- taint


class _TaintSolver:
    """Interprocedural device-taint fixpoint over function summaries.

    Per-function interface: ``returns_device`` (calling it yields a device
    value), ``param_returns`` (params whose taint flows to the return),
    ``param_escapes`` (params whose taint reaches a host conversion inside
    the function), and the list of *definite* escapes (host conversions of
    values device-tainted no matter the caller)."""

    def __init__(self, graph: ProjectGraph) -> None:
        self.graph = graph
        self.returns_device: dict[str, bool] = {}
        self.param_returns: dict[str, set[int]] = {}
        self.param_escapes: dict[str, dict[int, tuple[int, str]]] = {}
        self.definite_escapes: dict[str, list[tuple[int, str, str]]] = {}
        self._call_index: dict[str, dict[tuple[str, int], list[str]]] = {}
        for qn in graph.functions:
            self.returns_device[qn] = graph.functions[qn].jit_entry
            self.param_returns[qn] = set()
            self.param_escapes[qn] = {}
            self.definite_escapes[qn] = []
            idx: dict[tuple[str, int], list[str]] = {}
            for cs, targets in graph.calls.get(qn, ()):
                if not cs.is_ref:
                    idx[(cs.name, cs.line)] = targets
            self._call_index[qn] = idx
        self._solve()

    # -- atom evaluation ---------------------------------------------------
    def eval_atoms(self, fs: FunctionSummary,
                   atoms: list[str]) -> tuple[bool, set[int]]:
        """(definitely tainted, params whose taint would make it so)."""
        definite = False
        params: set[int] = set()
        seen: set[str] = set()

        def walk(atom_list: list[str]) -> None:
            nonlocal definite
            for a in atom_list:
                if a in seen:
                    continue
                seen.add(a)
                if a == "dev":
                    definite = True
                elif a.startswith("param:"):
                    params.add(int(a.split(":", 1)[1]))
                elif a.startswith("var:"):
                    walk(fs.assigns.get(a.split(":", 1)[1], []))
                elif a.startswith("callname:"):
                    _, name, line = a.split(":", 2)
                    for tgt in self._call_index[fs.qualname].get(
                            (name, int(line)), ()):
                        if self.returns_device.get(tgt):
                            definite = True
                        elif self.param_returns.get(tgt):
                            # the callee pipes some param to its return:
                            # taint depends on the matching args
                            cs = self._site(fs, name, int(line))
                            if cs is not None:
                                for i in self.param_returns[tgt]:
                                    walk(cs.arg_atoms.get(str(i), []))
        walk(atoms)
        return definite, params

    def _site(self, fs: FunctionSummary, name: str,
              line: int) -> CallSite | None:
        for cs in fs.calls:
            if not cs.is_ref and cs.name == name and cs.line == line:
                return cs
        return None

    # -- fixpoint ----------------------------------------------------------
    def _solve(self) -> None:
        for _ in range(12):  # summaries converge in a few rounds
            changed = False
            for qn, fs in self.graph.functions.items():
                # returns
                definite, params = self.eval_atoms(fs, fs.returns)
                if definite and not self.returns_device[qn]:
                    self.returns_device[qn] = True
                    changed = True
                if not params <= self.param_returns[qn]:
                    self.param_returns[qn] |= params
                    changed = True
                # own escapes
                esc: list[tuple[int, str, str]] = []
                for e in fs.escapes:
                    d, p = self.eval_atoms(fs, e.atoms)
                    if d:
                        esc.append((e.line, e.conv, "device value"))
                    for i in p:
                        if i not in self.param_escapes[qn]:
                            self.param_escapes[qn][i] = (e.line, e.conv)
                            changed = True
                # escapes through callees: tainted arg into a param the
                # callee converts (the wrapper-laundering path)
                for cs, targets in self.graph.calls.get(qn, ()):
                    if cs.is_ref:
                        continue
                    for tgt in targets:
                        if self._allowed(tgt):
                            continue  # _host_pull(x) is the sanctioned funnel
                        # snapshot: a self-recursive fn (tgt == qn) would
                        # otherwise mutate the dict mid-iteration
                        for i, (eline, conv) in list(self.param_escapes.get(
                                tgt, {}).items()):
                            atoms = cs.arg_atoms.get(str(i), [])
                            if not atoms:
                                continue
                            d, p = self.eval_atoms(fs, atoms)
                            if d:
                                esc.append((
                                    cs.line, conv,
                                    f"via {_fn_label(tgt)}() which converts "
                                    f"its arg at line {eline}"))
                            for j in p:
                                if j not in self.param_escapes[qn]:
                                    self.param_escapes[qn][j] = (cs.line, conv)
                                    changed = True
                if esc != self.definite_escapes[qn]:
                    self.definite_escapes[qn] = esc
                    changed = True
            if not changed:
                break

    def _allowed(self, qn: str) -> bool:
        return self.graph.functions[qn].name in _HOST_TRANSFER_ALLOWED


def _allowlist_closure(graph: ProjectGraph) -> set[str]:
    """Functions allowed to host-convert device data: the named KB111
    materialization points, plus helpers reachable ONLY from allowed
    functions (a private helper of `_host_pull` inherits its license; a
    helper any stray path can reach does not)."""
    allowed = {qn for qn, fs in graph.functions.items()
               if fs.name in _HOST_TRANSFER_ALLOWED}
    changed = True
    while changed:
        changed = False
        for qn, fs in graph.functions.items():
            if qn in allowed:
                continue
            callers = graph.callers.get(qn, set())
            if callers and callers <= allowed:
                allowed.add(qn)
                changed = True
    return allowed


def _kb114(graph: ProjectGraph, taint: _TaintSolver) -> Iterable[Finding]:
    allowed = _allowlist_closure(graph)
    for qn, fs in graph.functions.items():
        rp = fs.relpath.replace("\\", "/")
        if not rp.startswith("kubebrain_tpu/storage/tpu/"):
            continue
        if qn in allowed:
            continue
        for line, conv, how in taint.definite_escapes.get(qn, ()):
            yield Finding(
                fs.relpath, line, 0, "KB114",
                f"device-array taint escapes to host through {conv} in "
                f"{_fn_label(qn)!r} ({how}); only the named materialization "
                f"points (_host_pull and friends) may pull device data")


# ----------------------------------------------------------- replica (119)

#: leader-only mutation surfaces (Class.method labels): the revision
#: dealers, the local sequencer's ring path, and the lease-state mutators.
#: A follower that reaches any of these would mint revisions or mutate
#: lease state the leader never sees — the split-brain KB119 exists to
#: make statically impossible (docs/replication.md). Adopting the
#: leader's committed floor (TSO.commit/init via ingest_replicated) is
#: deliberately NOT here: that is how a follower follows.
_KB119_LEADER_ONLY = frozenset({
    "TSO.deal", "TSO.deal_block",
    "Backend._notify", "Backend._notify_many", "Backend._drain",
    "LeaseRegistry.grant", "LeaseRegistry.keepalive",
    "LeaseReaper.revoke",
})

_KB119_ROOT = "kubebrain_tpu/replica/"


def _kb119(graph: ProjectGraph) -> Iterable[Finding]:
    """Any function defined under kubebrain_tpu/replica/ whose resolved
    call graph reaches a leader-only mutation surface. Reverse BFS from
    the forbidden targets (shortest witness chains), then one pass over
    replica call sites — same over-approximation-on-resolved-edges-only
    contract as KB112: unresolved calls are counted in stats, not
    guessed."""
    witness: dict[str, list[str]] = {}
    frontier: list[str] = []
    for qn in graph.functions:
        if _fn_label(qn) in _KB119_LEADER_ONLY:
            witness[qn] = [qn]
            frontier.append(qn)
    while frontier:
        nxt: list[str] = []
        for qn in frontier:
            chain = witness[qn]
            for caller in graph.callers.get(qn, ()):
                if caller in witness:
                    continue
                for cs, targets in graph.calls.get(caller, ()):
                    if not cs.is_ref and qn in targets:
                        witness[caller] = [caller] + chain
                        nxt.append(caller)
                        break
        frontier = nxt
    for qn, fs in graph.functions.items():
        rp = fs.relpath.replace("\\", "/")
        if not rp.startswith(_KB119_ROOT):
            continue
        for cs, targets in graph.calls.get(qn, ()):
            if cs.is_ref:
                continue
            for tgt in targets:
                w = witness.get(tgt)
                if w is None:
                    continue
                yield Finding(
                    fs.relpath, cs.line, cs.col, "KB119",
                    f"leader-only mutation surface reachable from follower-"
                    f"role module: {_fn_label(qn)} -> {_chain_str(w)} "
                    f"(replica/ code must never deal revisions, run the "
                    f"local sequencer, or mutate lease state)")
                break  # one finding per call site


# ------------------------------------------- field races (KB120–KB122)

_WRITE_KINDS = ("write", "augwrite")


@dataclasses.dataclass
class _FieldSite:
    """One field access with its EFFECTIVE lock context: the lexical stack
    at the access plus the locks provably held at every resolved call into
    the enclosing function (the must-hold entry set)."""

    fs: FunctionSummary
    acc: AttrAccess
    eff: frozenset[str]


def _is_spawn_name(name: str) -> bool:
    tail = name.split(".")[-1]
    return tail in _CALLBACK_SINKS or tail.endswith("_rpc_method_handler")


def _thread_roots(graph: ProjectGraph) -> dict[str, str]:
    """fn qualname -> why it runs off the constructing thread: references
    passed to a spawn/callback sink (Thread/Timer/submit/..., gRPC
    ``*_rpc_method_handler`` glue) — directly or through a project
    forwarder that pipes its own parameter into one — plus ``run`` methods
    of ``threading.Thread`` subclasses."""
    # forwarders: _unary(fn, ...) -> grpc.unary_unary_rpc_method_handler(fn)
    fwd: set[str] = set()
    changed = True
    while changed:
        changed = False
        for qn, fs in graph.functions.items():
            if qn in fwd:
                continue
            resolved = graph.calls.get(qn, ())
            for cs in fs.calls:
                if not cs.is_ref or cs.name not in fs.params:
                    continue
                hit = _is_spawn_name(cs.ref_of)
                if not hit:
                    for cs2, targets in resolved:
                        if (not cs2.is_ref and cs2.name == cs.ref_of
                                and set(targets) & fwd):
                            hit = True
                            break
                if hit:
                    fwd.add(qn)
                    changed = True
                    break
    roots: dict[str, str] = {}
    for qn, fs in graph.functions.items():
        resolved = graph.calls.get(qn, ())
        for cs, targets in resolved:
            if not cs.is_ref or not cs.ref_of:
                continue
            entering = _is_spawn_name(cs.ref_of)
            if not entering:
                for cs2, tgts2 in resolved:
                    if (not cs2.is_ref and cs2.name == cs.ref_of
                            and set(tgts2) & fwd):
                        entering = True
                        break
            if entering:
                for tgt in targets:
                    roots.setdefault(
                        tgt, f"{cs.ref_of}(...) at {fs.relpath}:{cs.line}")
    for ms in graph.modules.values():
        for cname, cinfo in ms.classes.items():
            if any(b.split(".")[-1] == "Thread" for b in cinfo["bases"]):
                qn = cinfo["methods"].get("run")
                if qn and qn in graph.functions:
                    roots.setdefault(qn, f"threading.Thread subclass {cname}")
    return roots


def _thread_escaped(graph: ProjectGraph,
                    roots: dict[str, str]) -> dict[str, list[str]]:
    """fn qualname -> witness chain from a thread-escape root (forward BFS
    over resolved non-ref edges — same resolved-edges-only contract as
    KB112: dynamic dispatch the resolver cannot see is counted in
    ``unresolved_calls``, not guessed)."""
    escaped: dict[str, list[str]] = {qn: [qn] for qn in roots}
    frontier = list(roots)
    while frontier:
        nxt: list[str] = []
        for qn in frontier:
            chain = escaped[qn]
            for cs, targets in graph.calls.get(qn, ()):
                if cs.is_ref:
                    continue
                for tgt in targets:
                    if tgt not in escaped:
                        escaped[tgt] = chain + [tgt]
                        nxt.append(tgt)
        frontier = nxt
    return escaped


def _entry_locks(graph: ProjectGraph,
                 roots: dict[str, str]) -> dict[str, frozenset[str]]:
    """Must-hold lock set on entry to each function: the intersection over
    every resolved call site of (caller's entry set + locks lexically held
    at the site). Thread-escape roots, module bodies, functions whose
    reference is passed around (invoked later in an unknown context), and
    functions with no resolved callers all enter with the empty set — a
    private helper only ever called under ``self._lock`` inherits the
    guard, a public method does not."""
    incoming: dict[str, list[tuple[str, frozenset[str]]]] = {}
    forced: set[str] = set(roots)
    for qn, fs in graph.functions.items():
        if fs.name == "<module>":
            forced.add(qn)
    for qn in graph.functions:
        for cs, targets in graph.calls.get(qn, ()):
            for tgt in targets:
                if cs.is_ref:
                    forced.add(tgt)
                else:
                    incoming.setdefault(tgt, []).append(
                        (qn, frozenset(cs.under_locks)))
    top = object()  # optimistic "not yet constrained"
    entry: dict[str, Any] = {}
    for qn in graph.functions:
        entry[qn] = (frozenset() if qn in forced or qn not in incoming
                     else top)
    changed = True
    while changed:
        changed = False
        for qn in graph.functions:
            if qn in forced or qn not in incoming:
                continue
            acc: Any = top
            for caller, locks in incoming[qn]:
                ce = entry.get(caller, frozenset())
                if ce is top:
                    continue
                val = ce | locks
                acc = val if acc is top else (acc & val)
            if acc is not top and acc != entry[qn]:
                entry[qn] = acc
                changed = True
    return {qn: (e if e is not top else frozenset())
            for qn, e in entry.items()}


def _field_table(graph: ProjectGraph,
                 entry: dict[str, frozenset[str]]
                 ) -> dict[str, list[_FieldSite]]:
    """'module::Class.attr' -> every access site with effective locks."""
    table: dict[str, list[_FieldSite]] = {}
    for qn, fs in graph.functions.items():
        ent = entry.get(qn, frozenset())
        for a in fs.attr_accesses:
            key = f"{fs.module}::{a.cls}.{a.attr}"
            table.setdefault(key, []).append(_FieldSite(
                fs=fs, acc=a, eff=frozenset(ent | set(a.under_locks))))
    for sites in table.values():
        sites.sort(key=lambda s: (s.fs.relpath, s.acc.line, s.acc.col))
    return table


def _publish_lines(graph: ProjectGraph) -> dict[str, float]:
    """'module::Class' -> first line in __init__ where self escapes (inf
    when the constructor never publishes self)."""
    pub: dict[str, float] = {}
    for qn, fs in graph.functions.items():
        if fs.name == "__init__" and fs.cls is not None:
            pub[f"{fs.module}::{fs.cls}"] = (
                float(min(fs.self_escape_lines))
                if fs.self_escape_lines else float("inf"))
    return pub


def _is_init_local(site: _FieldSite, pub: dict[str, float]) -> bool:
    """Constructor accesses before self escapes happen-before every other
    thread can hold the object — not race sites (the RacerD ownership
    exemption)."""
    fs, a = site.fs, site.acc
    if fs.name != "__init__" or fs.cls != a.cls:
        return False
    return a.line < pub.get(f"{fs.module}::{a.cls}", float("inf"))


def _immutable_fields(table: dict[str, list[_FieldSite]],
                      pub: dict[str, float]) -> set[str]:
    """Fields only ever written in __init__ before self escapes are
    publish-immutable: reads anywhere are safe without any lock."""
    out: set[str] = set()
    for key, sites in table.items():
        writes = [s for s in sites if s.acc.kind in _WRITE_KINDS]
        if writes and all(_is_init_local(s, pub) for s in writes):
            out.add(key)
    return out


def _field_label(key: str) -> str:
    return key.rsplit("::", 1)[-1]


def _site_str(s: _FieldSite) -> str:
    return f"{s.fs.relpath}:{s.acc.line}"


def _guard_str(eff: frozenset[str]) -> str:
    return "{" + ", ".join(sorted(eff)) + "}" if eff else "no lock"


def _field_races(graph: ProjectGraph,
                 escaped: dict[str, list[str]],
                 roots: dict[str, str],
                 table: dict[str, list[_FieldSite]],
                 pub: dict[str, float],
                 immutable: set[str]) -> Iterable[Finding]:
    """KB120 + KB121 over the field table. One finding per field (the
    first qualifying pair in deterministic order), KB121 suppressed on
    fields KB120 already flags (the stronger claim subsumes it)."""
    for key in sorted(table):
        if key in immutable or _LOCK_NAME_RE.search(key):
            continue
        sites = [s for s in table[key] if not _is_init_local(s, pub)]
        if not sites or not sites[0].fs.relpath.replace(
                "\\", "/").startswith("kubebrain_tpu/"):
            continue
        guarded_writes = [s for s in sites
                          if s.acc.kind in _WRITE_KINDS and s.eff]
        # ---- KB120: guarded write vs no-common-lock access, where the
        # concurrency is real — the access itself runs in a thread-
        # escaping context, OR it is a WRITE racing a thread-escaping
        # guarded writer (the post-publication constructor-tail shape)
        fired_120 = False
        for s in sites:
            chain = escaped.get(s.fs.qualname)
            if chain is None and s.acc.kind in _WRITE_KINDS:
                for w in guarded_writes:
                    if w.fs.qualname in escaped and not (w.eff & s.eff) \
                            and (w.fs.relpath, w.acc.line) != (
                                s.fs.relpath, s.acc.line):
                        chain = escaped[w.fs.qualname]
                        break
            if chain is None:
                continue
            for w in guarded_writes:
                if (w.fs.relpath, w.acc.line) == (s.fs.relpath, s.acc.line):
                    continue
                if w.eff & s.eff:
                    continue
                root_why = roots.get(chain[0], "thread entry")
                via = (_chain_str(chain) if len(chain) > 1
                       else _fn_label(chain[0]))
                yield Finding(
                    s.fs.relpath, s.acc.line, s.acc.col, "KB120",
                    f"field {_field_label(key)} written under "
                    f"{_guard_str(w.eff)} at {_site_str(w)} but "
                    f"{s.acc.kind} here holds {_guard_str(s.eff)} in a "
                    f"thread-escaping context (enters via {root_why}: "
                    f"{via})")
                fired_120 = True
                break
            if fired_120:
                break
        if fired_120:
            continue
        # ---- KB121: a guarded WRITE and another guarded access with NO
        # lock in common — both sites believe the field is protected, but
        # by different locks. Pairwise on purpose: a write under the
        # UNION of several locks shares a guard with a reader under any
        # one of them (the multi-condition close-latch shape) and is
        # consistent, which a global-intersection test would miss-flag.
        guarded = [s for s in sites if s.eff]
        pair = None
        for w in guarded_writes:
            for s in guarded:
                if (w.fs.relpath, w.acc.line) == (s.fs.relpath, s.acc.line):
                    continue
                if not (w.eff & s.eff):
                    pair = (w, s)
                    break
            if pair:
                break
        if pair:
            w, s = pair
            yield Finding(
                w.fs.relpath, w.acc.line, w.acc.col, "KB121",
                f"field {_field_label(key)} is guarded by DIFFERENT locks "
                f"at different sites: {_guard_str(w.eff)} at {_site_str(w)}"
                f" vs {_guard_str(s.eff)} at {_site_str(s)} — no common "
                f"guard, so the two sites do not exclude each other")


def _check_then_act(graph: ProjectGraph,
                    escaped: dict[str, list[str]],
                    table: dict[str, list[_FieldSite]],
                    pub: dict[str, float],
                    immutable: set[str]) -> Iterable[Finding]:
    """KB122: inside one function, a guarded read of a shared field and a
    later write to it under a SEPARATE acquisition of the same lock — the
    lock was released across the decision, so the read's justification is
    stale by the time the write lands. Shared = some other function also
    writes the field, or this function itself thread-escapes (two threads
    run the same check concurrently)."""
    # claim-flag index for the claimed_across exemption: function -> every
    # (field, lock, acq_line) it writes under a lock. A ticketed
    # singleflight claims a COMPANION flag inside the read's hold
    # (`self._fl_inflight = True`) and resets it inside the write's hold —
    # that bracket makes this function the sole owner of the released
    # window, so the re-acquiring write cannot act on a stale read.
    fn_writes: dict[str, set[tuple[str, str, int]]] = {}
    for key2, sites2 in table.items():
        for s in sites2:
            if s.acc.kind in _WRITE_KINDS:
                for l2, a2 in zip(s.acc.under_locks, s.acc.acq_lines):
                    fn_writes.setdefault(s.fs.qualname, set()).add(
                        (key2, l2, a2))
    for key in sorted(table):
        if key in immutable or _LOCK_NAME_RE.search(key):
            continue
        sites = [s for s in table[key] if not _is_init_local(s, pub)]
        by_fn: dict[str, list[_FieldSite]] = {}
        writers: set[str] = set()
        for s in sites:
            by_fn.setdefault(s.fs.qualname, []).append(s)
            if s.acc.kind in _WRITE_KINDS:
                writers.add(s.fs.qualname)
        for qn, fn_sites in sorted(by_fn.items()):
            if not fn_sites[0].fs.relpath.replace(
                    "\\", "/").startswith("kubebrain_tpu/"):
                continue
            shared = bool(writers - {qn}) or qn in escaped
            if not shared:
                continue
            reads = [s for s in fn_sites if s.acc.kind == "read"
                     and s.acc.under_locks]
            writes = [s for s in fn_sites if s.acc.kind in _WRITE_KINDS
                      and s.acc.under_locks]
            done: set[tuple[str, str]] = set()
            for r in reads:
                for w in writes:
                    if w.acc.line <= r.acc.line:
                        continue
                    for lock in set(r.acc.under_locks) & set(
                            w.acc.under_locks):
                        r_acq = r.acc.acq_lines[
                            r.acc.under_locks.index(lock)]
                        # the read's OWN block also writes the field: the
                        # check acted atomically under that hold (flag
                        # claim / ownership transfer — `if not busy: busy
                        # = True`); a later write is a state reset by the
                        # owner, not a stale-decision act
                        acted_inline = any(
                            w0.acc.kind in _WRITE_KINDS
                            and (lock, r_acq) in zip(w0.acc.under_locks,
                                                     w0.acc.acq_lines)
                            for w0 in fn_sites)
                        if acted_inline:
                            continue
                        w_acqs = [w.acc.acq_lines[i]
                                  for i, l in enumerate(w.acc.under_locks)
                                  if l == lock]
                        if r_acq in w_acqs:
                            continue  # same (or enclosing) acquisition
                        # a DIFFERENT lock held across both blocks (same
                        # acquisition) protects the whole decision window
                        # — the checkpoint-under-_ckpt_lock shape
                        held_across = False
                        for i, l2 in enumerate(r.acc.under_locks):
                            if l2 == lock:
                                continue
                            pair = (l2, r.acc.acq_lines[i])
                            if pair in zip(w.acc.under_locks,
                                           w.acc.acq_lines):
                                held_across = True
                                break
                        if held_across:
                            continue
                        # the write's own block RE-READS the field before
                        # writing: the double-checked publish pattern
                        # (snapshot -> expensive work off-lock -> reacquire,
                        # re-validate, swap) is the sanctioned shape, not a
                        # stale-decision bug
                        revalidated = any(
                            r2.acc.kind == "read"
                            and r2.acc.line <= w.acc.line
                            and any(l == lock and a not in (r_acq,)
                                    and a in w_acqs
                                    for l, a in zip(r2.acc.under_locks,
                                                    r2.acc.acq_lines))
                            for r2 in fn_sites)
                        if revalidated:
                            continue
                        # a companion field written under BOTH the read's
                        # acquisition and the write's re-acquisition is the
                        # claim/reset bracket of a ticketed singleflight:
                        # only the claimant reaches this write, so the
                        # released window is exclusively owned
                        wset = fn_writes.get(qn, set())
                        claimed_across = any(
                            k2 != key and (k2, lock, r_acq) in wset
                            and any((k2, lock, wa) in wset for wa in w_acqs)
                            for (k2, _l, _a) in wset)
                        if claimed_across:
                            continue
                        if (qn, lock) in done:
                            continue
                        done.add((qn, lock))
                        yield Finding(
                            w.fs.relpath, w.acc.line, w.acc.col, "KB122",
                            f"check-then-act on {_field_label(key)}: read "
                            f"at line {r.acc.line} under {lock} (acquired "
                            f"line {r_acq}), but this dependent write "
                            f"re-acquires it at line {w_acqs[0]} — the "
                            f"lock was released across the decision")


def _runtime_guard_sites(graph: ProjectGraph,
                         eff: Iterable[str]) -> list[str]:
    """Map static lock ids to lockcheck/fieldcheck construction-site keys
    ('pkg/file.py:NN') where the construction site is known."""
    out = []
    for lock_id in eff:
        site = graph.lock_sites.get(lock_id)
        if site is None:
            continue
        rp, line = site
        parts = rp.replace("\\", "/").split("/")
        out.append(f"{parts[-2]}/{parts[-1]}:{line}" if len(parts) >= 2
                   else f"{parts[-1]}:{line}")
    return sorted(out)


def _field_guard_report(graph: ProjectGraph,
                        table: dict[str, list[_FieldSite]],
                        pub: dict[str, float],
                        immutable: set[str],
                        escaped: dict[str, list[str]],
                        runtime_fields: list[dict] | None
                        ) -> dict[str, Any]:
    """The KB115-style cross-check report: static-inferred guard per
    written field vs the guard sets util/fieldcheck.py observed at
    runtime. Static guard = intersection of effective locks over all
    post-init write sites."""
    static: dict[str, dict[str, Any]] = {}
    for key, sites in sorted(table.items()):
        if key in immutable or _LOCK_NAME_RE.search(key):
            continue
        # steady-state writes only: the runtime sanitizer suppresses ALL
        # constructor writes (it cannot see escape lines), so the static
        # side of the comparison excludes __init__ entirely — comparing
        # post-publication guards on both sides
        writes = [s for s in sites if s.acc.kind in _WRITE_KINDS
                  and not (s.fs.name == "__init__"
                           and s.fs.cls == s.acc.cls)]
        if not writes:
            continue
        guard = frozenset.intersection(*[s.eff for s in writes])
        static[key] = {
            "write_sites": len(writes),
            "guards": sorted(guard),
            "guard_sites": _runtime_guard_sites(graph, guard),
            "thread_escaping": any(s.fs.qualname in escaped
                                   for s in sites),
        }
    report: dict[str, Any] = {
        "static_written_fields": len(static),
        "publish_immutable_fields": len(immutable),
        "static": static,
    }
    if runtime_fields is not None:
        observed = {f["key"]: f for f in runtime_fields if "key" in f}
        matched = sorted(set(static) & set(observed))
        agreements: list[str] = []
        mismatches: list[dict[str, Any]] = []
        for key in matched:
            s_sites = set(static[key]["guard_sites"])
            r_sites = set(observed[key].get("guards", []))
            if s_sites == r_sites:
                agreements.append(key)
            else:
                mismatches.append({
                    "field": key,
                    "static_guard_sites": sorted(s_sites),
                    "runtime_guard_sites": sorted(r_sites),
                    "runtime_threads": observed[key].get("threads", 0),
                })
        report.update({
            "observed_fields": len(observed),
            "matched_fields": len(matched),
            "agreements": len(agreements),
            "mismatches": mismatches,
            # fields the static tier tracks that no runtime run has ever
            # written under the sanitizer — the sanitizer's coverage gap,
            # exactly like KB115's static_edges_unobserved
            "static_only_fields": sorted(set(static) - set(observed)),
            "runtime_only_fields": sorted(set(observed) - set(static)),
            "coverage": (len(matched) / len(static) if static else 1.0),
        })
    return report


# -------------------------------------------------------------- lock order


def _acquired_closure(graph: ProjectGraph) -> dict[str, dict[str, list[str]]]:
    """fn -> {lock_id: witness chain of functions leading to the acquire}."""
    acq: dict[str, dict[str, list[str]]] = {
        qn: {} for qn in graph.functions}
    for qn, fs in graph.functions.items():
        for a in fs.acquires:
            acq[qn].setdefault(a.lock_id, [qn])
    changed = True
    while changed:
        changed = False
        for qn, fs in graph.functions.items():
            for cs, targets in graph.calls.get(qn, ()):
                if cs.is_ref:
                    continue
                for tgt in targets:
                    for lock_id, chain in acq.get(tgt, {}).items():
                        if lock_id not in acq[qn]:
                            acq[qn][lock_id] = [qn] + chain
                            changed = True
    return acq


def _lock_edges(graph: ProjectGraph,
                acquired: dict[str, dict[str, list[str]]]
                ) -> dict[tuple[str, str], tuple[str, int, str]]:
    """(held, acquired) -> (relpath, line, witness description)."""
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    for qn, fs in graph.functions.items():
        for a in fs.acquires:
            for held in a.under_locks:
                if held != a.lock_id:
                    edges.setdefault(
                        (held, a.lock_id),
                        (fs.relpath, a.line, f"nested with in {_fn_label(qn)}"))
        for cs, targets in graph.calls.get(qn, ()):
            if cs.is_ref or not cs.under_locks:
                continue
            for tgt in targets:
                for lock_id, chain in acquired.get(tgt, {}).items():
                    for held in cs.under_locks:
                        if held != lock_id:
                            edges.setdefault(
                                (held, lock_id),
                                (fs.relpath, cs.line,
                                 f"{_fn_label(qn)} -> {_chain_str(chain)}"))
    return edges


def _find_cycles(edges: Iterable[tuple[str, str]]) -> list[list[str]]:
    """Elementary cycles via SCC + DFS (the graphs here are tiny)."""
    adj: dict[str, set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
    cycles: list[list[str]] = []
    seen_keys: set[tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: list[str], visited: set[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start and len(path) > 1:
                key = tuple(sorted(path))
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(list(path))
            elif nxt not in visited and nxt > start:
                # only explore nodes > start so each cycle is found once,
                # rooted at its smallest node
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return cycles


def _runtime_site_map(graph: ProjectGraph) -> dict[str, str]:
    """lockcheck creation-site string ('pkg/file.py:NN') -> static lock id.
    lockcheck keys sites as basename(dirname)/basename(file):line."""
    out: dict[str, str] = {}
    for lock_id, (rp, line) in graph.lock_sites.items():
        rp = rp.replace("\\", "/")
        parts = rp.split("/")
        site = (f"{parts[-2]}/{parts[-1]}:{line}" if len(parts) >= 2
                else f"{parts[-1]}:{line}")
        out[site] = lock_id
    return out


def _kb115(graph: ProjectGraph,
           runtime_edges: list[tuple[str, str]] | None
           ) -> tuple[list[Finding], dict[str, Any]]:
    acquired = _acquired_closure(graph)
    edges = _lock_edges(graph, acquired)
    findings: list[Finding] = []
    for cyc in _find_cycles(edges.keys()):
        chain = cyc + [cyc[0]]
        first = edges.get((cyc[0], cyc[1])) or next(iter(edges.values()))
        findings.append(Finding(
            first[0], first[1], 0, "KB115",
            "static lock-order cycle (potential ABBA deadlock): "
            + " -> ".join(chain) + f"; first edge via {first[2]}"))

    report: dict[str, Any] = {
        "static_edges": sorted(f"{a} -> {b}" for a, b in edges),
        "static_edge_count": len(edges),
        "cycles": len(findings),
        "lock_sites": len(graph.lock_sites),
    }
    if runtime_edges is not None:
        site_map = _runtime_site_map(graph)
        mapped: list[tuple[str, str]] = []
        unmapped = 0
        for a, b in runtime_edges:
            la, lb = site_map.get(a), site_map.get(b)
            if la and lb:
                mapped.append((la, lb))
            else:
                unmapped += 1
        static_set = set(edges.keys())
        runtime_set = set(mapped)
        report.update({
            "runtime_edges": len(runtime_edges),
            "runtime_edges_mapped": len(mapped),
            "runtime_edges_unmapped_sites": unmapped,
            # the runtime detector's coverage gap, now measurable: static
            # edges no runtime run has ever exercised
            "static_edges_unobserved": sorted(
                f"{a} -> {b}" for a, b in static_set - runtime_set),
            # static blindness: orders the runtime saw that resolution
            # missed (unresolved calls / dynamic dispatch)
            "runtime_only_edges": sorted(
                f"{a} -> {b}" for a, b in runtime_set - static_set),
            "coverage": (len(static_set & runtime_set) / len(static_set)
                         if static_set else 1.0),
        })
    return findings, report


# ------------------------------------------------------------------ driver


def analyze(graph: ProjectGraph,
            runtime_lock_edges: list[tuple[str, str]] | None = None,
            runtime_field_obs: list[dict] | None = None,
            sources: dict[str, str] | None = None,
            runtime_leak_obs: list[dict] | None = None) -> DeepResult:
    """Run all context propagations and the KB112–KB126 rules. The CFG
    tier (KB123–KB126) needs raw sources to lower — when ``sources`` is
    None those rules are skipped (summary-only replay has no ASTs)."""
    blocking = _blocking_witness(graph)
    traced = _traced_set(graph)
    taint = _TaintSolver(graph)
    roots = _thread_roots(graph)
    escaped = _thread_escaped(graph, roots)
    entry = _entry_locks(graph, roots)
    table = _field_table(graph, entry)
    pub = _publish_lines(graph)
    immutable = _immutable_fields(table, pub)

    findings: list[Finding] = []
    findings.extend(_kb112(graph, blocking))
    findings.extend(_kb113(graph, traced, taint))
    findings.extend(_kb114(graph, taint))
    kb115, lock_graph = _kb115(graph, runtime_lock_edges)
    findings.extend(kb115)
    findings.extend(_kb119(graph))
    findings.extend(_field_races(graph, escaped, roots, table, pub,
                                 immutable))
    findings.extend(_check_then_act(graph, escaped, table, pub, immutable))
    field_guards = _field_guard_report(graph, table, pub, immutable,
                                      escaped, runtime_field_obs)

    leak_stats: dict[str, int] = {}
    leaks: dict[str, Any] = {}
    if sources is not None:
        from .cfg import analyze_leaks, leak_report
        kb_leaks, leak_stats, static_leaks = analyze_leaks(graph, sources)
        findings.extend(kb_leaks)
        leaks = leak_report(static_leaks, runtime_leak_obs)

    # suppression pragmas (flagged line or the comment line above it)
    by_rel = {ms.relpath: ms for ms in graph.modules.values()}
    kept: list[Finding] = []
    for f in findings:
        ms = by_rel.get(f.path)
        if ms is not None:
            if f.rule_id in ms.file_disabled:
                continue
            if f.rule_id in ms.disabled_lines.get(str(f.line), []):
                continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule_id))

    async_fns = _async_reachable(graph)
    stats = dict(graph.stats.as_dict())
    stats.update({
        "blocking_reachable": len(blocking),
        "traced_functions": len(traced),
        "async_reachable": len(async_fns),
        "lock_edges": lock_graph["static_edge_count"],
        "thread_roots": len(roots),
        "thread_escaped": len(escaped),
        "tracked_fields": len(table),
        "publish_immutable_fields": len(immutable),
        "field_access_sites": sum(len(v) for v in table.values()),
    })
    stats.update(leak_stats)
    return DeepResult(findings=kept, stats=stats, lock_graph=lock_graph,
                      field_guards=field_guards, leaks=leaks)


def _async_reachable(graph: ProjectGraph) -> set[str]:
    """Functions executing on the event loop: coroutines plus sync
    functions they call directly (refs — executor thunks, callbacks —
    excluded)."""
    out = {qn for qn, fs in graph.functions.items() if fs.is_async}
    frontier = list(out)
    while frontier:
        nxt = []
        for qn in frontier:
            for cs, targets in graph.calls.get(qn, ()):
                if cs.is_ref:
                    continue
                for tgt in targets:
                    if tgt not in out:
                        out.add(tgt)
                        nxt.append(tgt)
        frontier = nxt
    return out
