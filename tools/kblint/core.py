"""Rule registry, suppression handling, and the lint driver."""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Iterable


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule_id: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}"


class Rule:
    """One project invariant. Subclasses set ``rule_id``/``summary`` and
    implement ``check(tree, src)`` yielding ``(node, message)`` pairs."""

    rule_id: str = ""
    summary: str = ""

    def applies(self, relpath: str) -> bool:
        """Whether this rule runs on the file at repo-relative ``relpath``."""
        return True

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        raise NotImplementedError


RULES: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    rule = rule_cls()
    if not rule.rule_id:
        raise ValueError(f"{rule_cls.__name__} has no rule_id")
    if rule.rule_id in RULES:
        raise ValueError(f"duplicate rule id {rule.rule_id}")
    RULES[rule.rule_id] = rule
    return rule_cls


_DISABLE_RE = re.compile(r"#\s*kblint:\s*disable=([A-Z0-9,\s]+?)(?:\s*--.*)?$")
_DISABLE_FILE_RE = re.compile(r"#\s*kblint:\s*disable-file=([A-Z0-9,\s]+?)(?:\s*--.*)?$")


def _disabled_on_line(line: str) -> set[str]:
    m = _DISABLE_RE.search(line)
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def _file_disabled(lines: list[str]) -> set[str]:
    out: set[str] = set()
    for line in lines[:20]:  # file-level pragmas live in the header
        m = _DISABLE_FILE_RE.search(line)
        if m:
            out |= {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _suppression_lines(node: ast.AST, tree: ast.Module) -> set[int]:
    """Lines whose disable comment covers ``node``: the node's own first
    line, the comment line directly above it, plus the header line of every
    enclosing with/def/async-def block (so one pragma on ``with
    self._lock:`` covers the whole block)."""
    covered = {getattr(node, "lineno", 0)}
    target_line = getattr(node, "lineno", 0)
    for parent in ast.walk(tree):
        if not isinstance(parent, (ast.With, ast.AsyncWith,
                                   ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        end = getattr(parent, "end_lineno", 0) or 0
        if parent.lineno <= target_line <= end:
            covered.add(parent.lineno)
    return covered


def lint_source(src: str, relpath: str, rules: Iterable[Rule] | None = None) -> list[Finding]:
    rules = list(rules if rules is not None else RULES.values())
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 0, e.offset or 0, "KB000",
                        f"syntax error: {e.msg}")]
    lines = src.splitlines()
    file_off = _file_disabled(lines)
    findings: list[Finding] = []
    for rule in rules:
        if rule.rule_id in file_off or not rule.applies(relpath):
            continue
        for node, message in rule.check(tree, src):
            line = getattr(node, "lineno", 0)
            col = getattr(node, "col_offset", 0)
            candidates = _suppression_lines(node, tree)
            # a pure comment line directly above the finding also counts
            if line >= 2 and lines[line - 2].lstrip().startswith("#"):
                candidates.add(line - 1)
            suppressed = any(
                rule.rule_id in _disabled_on_line(lines[ln - 1])
                for ln in candidates if 1 <= ln <= len(lines)
            )
            if not suppressed:
                findings.append(Finding(relpath, line, col, rule.rule_id, message))
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def iter_py_files(paths: list[str], root: str) -> Iterable[str]:
    skip_dirs = {".git", "__pycache__", ".claude", "node_modules"}
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            yield ap
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in dirnames if d not in skip_dirs]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_paths(paths: list[str], root: str | None = None) -> list[Finding]:
    root = root or os.getcwd()
    findings: list[Finding] = []
    for ap in iter_py_files(paths, root):
        relpath = os.path.relpath(ap, root)
        try:
            with open(ap, encoding="utf-8") as f:
                src = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(relpath, 0, 0, "KB000", f"unreadable: {e}"))
            continue
        findings.extend(lint_source(src, relpath))
    return findings
