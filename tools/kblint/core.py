"""Rule registry, suppression handling, baselines, and the lint drivers
(syntactic per-file tier + the interprocedural deep tier)."""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time
from typing import Any, Iterable


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    rule_id: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}"


class Rule:
    """One project invariant. Subclasses set ``rule_id``/``summary`` and
    implement ``check(tree, src)`` yielding ``(node, message)`` pairs."""

    rule_id: str = ""
    summary: str = ""

    def applies(self, relpath: str) -> bool:
        """Whether this rule runs on the file at repo-relative ``relpath``."""
        return True

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        raise NotImplementedError


RULES: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    rule = rule_cls()
    if not rule.rule_id:
        raise ValueError(f"{rule_cls.__name__} has no rule_id")
    if rule.rule_id in RULES:
        raise ValueError(f"duplicate rule id {rule.rule_id}")
    RULES[rule.rule_id] = rule
    return rule_cls


_DISABLE_RE = re.compile(r"#\s*kblint:\s*disable=([A-Z0-9,\s]+?)(?:\s*--.*)?$")
_DISABLE_FILE_RE = re.compile(r"#\s*kblint:\s*disable-file=([A-Z0-9,\s]+?)(?:\s*--.*)?$")


def _disabled_on_line(line: str) -> set[str]:
    m = _DISABLE_RE.search(line)
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def _file_disabled(lines: list[str]) -> set[str]:
    out: set[str] = set()
    for line in lines[:20]:  # file-level pragmas live in the header
        m = _DISABLE_FILE_RE.search(line)
        if m:
            out |= {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _suppression_lines(node: ast.AST, tree: ast.Module) -> set[int]:
    """Lines whose disable comment covers ``node``: the node's own first
    line, the comment line directly above it, plus the header line of every
    enclosing with/def/async-def block (so one pragma on ``with
    self._lock:`` covers the whole block)."""
    covered = {getattr(node, "lineno", 0)}
    target_line = getattr(node, "lineno", 0)
    for parent in ast.walk(tree):
        if not isinstance(parent, (ast.With, ast.AsyncWith,
                                   ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        end = getattr(parent, "end_lineno", 0) or 0
        if parent.lineno <= target_line <= end:
            covered.add(parent.lineno)
    return covered


def lint_source(src: str, relpath: str, rules: Iterable[Rule] | None = None) -> list[Finding]:
    rules = list(rules if rules is not None else RULES.values())
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 0, e.offset or 0, "KB000",
                        f"syntax error: {e.msg}")]
    lines = src.splitlines()
    file_off = _file_disabled(lines)
    findings: list[Finding] = []
    for rule in rules:
        if rule.rule_id in file_off or not rule.applies(relpath):
            continue
        for node, message in rule.check(tree, src):
            line = getattr(node, "lineno", 0)
            col = getattr(node, "col_offset", 0)
            candidates = _suppression_lines(node, tree)
            # a pure comment line directly above the finding also counts
            if line >= 2 and lines[line - 2].lstrip().startswith("#"):
                candidates.add(line - 1)
            suppressed = any(
                rule.rule_id in _disabled_on_line(lines[ln - 1])
                for ln in candidates if 1 <= ln <= len(lines)
            )
            if not suppressed:
                findings.append(Finding(relpath, line, col, rule.rule_id, message))
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def iter_py_files(paths: list[str], root: str) -> Iterable[str]:
    skip_dirs = {".git", "__pycache__", ".claude", "node_modules"}
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            yield ap
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in dirnames if d not in skip_dirs]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_paths(paths: list[str], root: str | None = None,
               cache: "Any | None" = None) -> list[Finding]:
    """Syntactic tier over files/dirs; ``cache`` (a LintCache) makes the
    sweep incremental — unchanged files replay their cached findings."""
    root = root or os.getcwd()
    findings: list[Finding] = []
    for ap in iter_py_files(paths, root):
        relpath = os.path.relpath(ap, root)
        try:
            with open(ap, encoding="utf-8") as f:
                src = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(relpath, 0, 0, "KB000", f"unreadable: {e}"))
            continue
        entry = cache.get(relpath, src) if cache is not None else None
        if entry is not None and "findings" in entry:
            findings.extend(
                Finding(relpath, f[0], f[1], f[2], f[3])
                for f in entry["findings"])
            continue
        file_findings = lint_source(src, relpath)
        if cache is not None:
            new_entry = dict(entry or {})
            new_entry["findings"] = [
                [f.line, f.col, f.rule_id, f.message] for f in file_findings]
            cache.put(relpath, src, new_entry)
        findings.extend(file_findings)
    return findings


# ------------------------------------------------------------------ baseline

_LINE_REF_RE = re.compile(r":\d+|\bline \d+")


def normalize_message(msg: str) -> str:
    """Baseline matching key: line numbers inside messages drift with
    unrelated edits, so they are masked out of the identity — both the
    ``path.py:NN`` form and KB114's ``at line NN`` form."""
    return _LINE_REF_RE.sub(":N", msg)


class Baseline:
    """Pinned pre-existing findings (tools/kblint/baseline.json).

    A baseline entry matches on (rule, path, normalized message) — NOT on
    the line number, which moves under unrelated edits. Baselined findings
    are reported as counts, not failures; entries that no longer fire are
    listed as stale so they get cleaned out rather than silently masking a
    future regression at the same spot."""

    def __init__(self, entries: list[dict], path: str | None = None) -> None:
        self.entries = entries
        self.path = path
        self._keys = {self._entry_key(e) for e in entries}

    @staticmethod
    def _entry_key(e: dict) -> tuple[str, str, str]:
        return (e["rule"], e["path"], normalize_message(e["message"]))

    @staticmethod
    def _finding_key(f: Finding) -> tuple[str, str, str]:
        return (f.rule_id, f.path, normalize_message(f.message))

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return cls([], path)
        return cls(list(data.get("findings", [])), path)

    def split(self, findings: list[Finding]
              ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """(new findings, baselined findings, stale baseline entries)."""
        new: list[Finding] = []
        pinned: list[Finding] = []
        fired: set[tuple[str, str, str]] = set()
        for f in findings:
            key = self._finding_key(f)
            if key in self._keys:
                pinned.append(f)
                fired.add(key)
            else:
                new.append(f)
        stale = [e for e in self.entries if self._entry_key(e) not in fired]
        return new, pinned, stale

    @classmethod
    def write(cls, path: str, findings: list[Finding],
              previous: "Baseline | None" = None) -> None:
        """Rewrite the baseline from the current findings, preserving the
        human justification of entries that keep firing."""
        whys: dict[tuple[str, str, str], str] = {}
        if previous is not None:
            for e in previous.entries:
                if e.get("why"):
                    whys[cls._entry_key(e)] = e["why"]
        entries = []
        seen: set[tuple[str, str, str]] = set()
        for f in sorted(findings, key=lambda f: (f.rule_id, f.path, f.line)):
            key = cls._finding_key(f)
            if key in seen:
                continue
            seen.add(key)
            entries.append({
                "rule": f.rule_id, "path": f.path, "line": f.line,
                "message": f.message,
                "why": whys.get(key, "TODO: justify or fix"),
            })
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({
                "version": 1,
                "note": ("Pinned pre-existing deep findings. Entries match "
                         "on (rule, path, message-with-line-numbers-masked);"
                         " fix the code or justify in 'why'. Regenerate with"
                         " python -m tools.kblint --deep --write-baseline."),
                "findings": entries,
            }, fh, indent=1)
            fh.write("\n")


# ---------------------------------------------------------------- deep tier

#: the deep tier's call-graph universe (relative to the repo root); the
#: syntactic tier keeps whatever paths the caller passes (tests included),
#: but tests are deliberately NOT in the call graph — fixture code full of
#: deliberate violations would drown the serving-path signal
DEEP_ROOTS = ["kubebrain_tpu", "tools", "bench.py"]


def deep_analyze_sources(sources: dict[str, str],
                         runtime_lock_edges: list | None = None,
                         runtime_field_obs: list | None = None,
                         runtime_leak_obs: list | None = None) -> Any:
    """Deep tier over in-memory {relpath: source} (the self-test entry):
    build summaries, stitch the graph, propagate, run KB112–KB126."""
    from .contexts import analyze
    from .graph import ProjectGraph, extract_module
    summaries = [extract_module(src, rp) for rp, src in sorted(sources.items())]
    graph = ProjectGraph(summaries)
    # [] is real data ("a run that nested nothing"), distinct from None
    # ("no runtime export supplied") — collapsing them would mask a
    # zero-coverage detector as "no data"
    edges = ([tuple(e) for e in runtime_lock_edges]
             if runtime_lock_edges is not None else None)
    return analyze(graph, runtime_lock_edges=edges,
                   runtime_field_obs=runtime_field_obs,
                   sources=dict(sources), runtime_leak_obs=runtime_leak_obs)


def deep_analyze_paths(root: str, roots: list[str] | None = None,
                       cache: "Any | None" = None,
                       runtime_lock_edges: list | None = None,
                       runtime_field_obs: list | None = None,
                       runtime_leak_obs: list | None = None) -> Any:
    """Deep tier over the repo tree. Per-file extraction rides the same
    content-hash cache as the syntactic tier (entry key "summary"). The
    sources read here are handed on to the CFG tier, which re-lowers the
    few files hosting acquire sites (cheap next to extraction)."""
    from .contexts import analyze
    from .graph import ModuleSummary, ProjectGraph, extract_module
    t0 = time.monotonic()
    summaries: list[ModuleSummary] = []
    sources: dict[str, str] = {}
    parsed = from_cache = 0
    for ap in iter_py_files(roots or DEEP_ROOTS, root):
        relpath = os.path.relpath(ap, root).replace("\\", "/")
        try:
            with open(ap, encoding="utf-8") as f:
                src = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        sources[relpath] = src
        entry = cache.get(relpath, src) if cache is not None else None
        if entry is not None and "summary" in entry:
            summaries.append(ModuleSummary.from_dict(entry["summary"]))
            from_cache += 1
            continue
        ms = extract_module(src, relpath)
        parsed += 1
        if cache is not None:
            new_entry = dict(entry or {})
            new_entry["summary"] = ms.to_dict()
            # keep the syntactic findings alongside so one entry serves
            # both tiers
            if "findings" not in new_entry:
                new_entry["findings"] = [
                    [f.line, f.col, f.rule_id, f.message]
                    for f in lint_source(src, relpath)]
            cache.put(relpath, src, new_entry)
        summaries.append(ms)
    graph = ProjectGraph(summaries)
    edges = ([tuple(e) for e in runtime_lock_edges]
             if runtime_lock_edges is not None else None)
    result = analyze(graph, runtime_lock_edges=edges,
                     runtime_field_obs=runtime_field_obs,
                     sources=sources, runtime_leak_obs=runtime_leak_obs)
    result.stats["files_parsed"] = parsed
    result.stats["files_from_cache"] = from_cache
    result.stats["elapsed_seconds"] = round(time.monotonic() - t0, 3)
    return result
