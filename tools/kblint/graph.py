"""Whole-program call graph for kblint's interprocedural tier.

Two phases, split so the first is cacheable per file (.kblint_cache/):

1. **Extraction** (:func:`extract_module`) — one AST walk per module
   producing a JSON-serializable :class:`ModuleSummary`: every function's
   call sites (with the lexical lock stack at each), lock acquisitions,
   host-sync ops, device-taint atoms, jit/shard_map entry marks, import
   and alias tables, and lock construction sites. Pure function of the
   source text, so a content-hash cache key is sound.

2. **Resolution** (:class:`ProjectGraph`) — stitches the summaries into a
   project-wide call graph. Best-effort by design: module functions,
   ``from``-imports, ``self.``/class-attribute methods (with attribute
   types inferred from ``self.x = ClassName(...)`` assignments),
   ``functools.partial``, module-level ``f = jax.jit(g)`` aliases, and a
   unique-method-name fallback. Everything it cannot resolve is *counted*
   (``stats.unresolved_calls``) rather than silently dropped — the
   analysis over-reports its own blindness instead of faking closure.

The context propagation and the KB112–KB122 rules live in contexts.py.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Any, Iterable

from .core import _DISABLE_FILE_RE, _DISABLE_RE
from .rules import dotted_name, terminal_name

#: functions treated as jit/trace entries when used as decorators or
#: wrappers (value position): their argument's body executes under tracing
_TRACE_WRAPPERS = {
    "jax.jit", "jit", "pjit", "jax.pjit", "shard_map", "jax.shard_map",
    "pl.pallas_call", "pallas_call", "jax.vmap", "vmap",
}

#: attribute names whose access on a device array yields host metadata,
#: not a device value (x.shape is a static tuple, never a transfer)
_UNTAINT_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "nbytes",
                  "at", "devices"}

#: host converters whose call on a device-tainted value is a device→host
#: transfer (the KB111/KB114 escape set)
_HOST_CONV_NAMES = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "np.copy", "numpy.copy", "float", "bytes",
}
_HOST_CONV_METHODS = {"tolist", "item"}

_LOCK_NAME_RE = re.compile(r"lock$", re.IGNORECASE)

#: calls whose function-reference arguments execute on ANOTHER thread (or a
#: deferred context): the thread-escape roots for the field-race rules
#: KB120–KB122. Matching is on the call's terminal name so both
#: ``threading.Thread(target=f)`` and ``self._pool.submit(f)`` register.
#: Arguments are walked one level deep, so ``Thread(target=crash_guard(
#: self._loop))`` still records ``self._loop`` as escaping.
_CALLBACK_SINKS = {
    "Thread", "Timer", "submit", "start_new_thread", "run_in_executor",
    "call_soon_threadsafe", "call_later", "add_done_callback",
}
# the suppression-pragma grammar is core.py's (one copy: a syntax change
# there must not leave the deep tier parsing the old grammar)

#: call-name roots that are NOT analysis blindness when unresolved
#: (builtins + the external modules this codebase leans on); hoisted to a
#: module constant — _counts_as_unresolved runs once per call site
import builtins as _builtins
_KNOWN_EXTERNAL_ROOTS = frozenset(dir(_builtins)) | frozenset({
    "jax", "jnp", "np", "numpy", "pl", "functools", "threading", "time",
    "os", "sys", "ast", "re", "grpc", "logging", "math", "json",
    "collections", "dataclasses", "itertools", "struct", "queue",
    "asyncio", "socket", "subprocess", "signal", "contextlib", "random",
    "hashlib", "shutil", "tempfile", "traceback", "typing", "enum", "abc",
    "io", "pickle", "base64", "zlib", "heapq", "bisect", "warnings",
    "weakref", "string", "textwrap", "argparse", "concurrent", "http",
    "urllib", "ssl", "select", "errno", "copy", "types", "inspect",
    "importlib", "pathlib", "platform", "uuid", "secrets", "statistics",
})


# --------------------------------------------------------------- summaries


@dataclasses.dataclass
class CallSite:
    """One call (or bare function reference) inside a function body."""

    line: int
    col: int
    name: str                 # dotted callee expression ("self.x.range_")
    under_locks: list[str]    # lock ids lexically held at this site
    is_ref: bool = False      # a bare reference passed around, not a call
    ref_of: str = ""          # for refs: the call the reference was passed to
    arg_atoms: dict[str, list[str]] = dataclasses.field(default_factory=dict)
    # taint atoms per positional arg index (str key for JSON)


@dataclasses.dataclass
class LockAcq:
    lock_id: str              # normalized lock identity (see _lock_identity)
    line: int
    under_locks: list[str]    # locks already held when this one is taken


@dataclasses.dataclass
class SyncOp:
    """A host-synchronization op (KB113's finding set)."""

    line: int
    op: str                   # "block_until_ready" | "item" | "device_get" |
    #                           "float" | "np.asarray" | ...
    atoms: list[str]          # taint atoms of the operand ([] = unknown)
    under_locks: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class EscapeOp:
    """A host conversion whose operand carries taint atoms (KB114)."""

    line: int
    conv: str                 # converter name (np.asarray, float, .item, ...)
    atoms: list[str]


@dataclasses.dataclass
class AttrAccess:
    """One ``self.x`` / ``cls.x`` field access inside a method body (the
    KB120–KB122 site record). ``under_locks`` is the lexical lock stack at
    the access; ``acq_lines`` is the parallel list of ``with``-statement
    lines those locks were taken at (KB122 distinguishes two separate
    acquisitions of the same lock in one function — the released window)."""

    line: int
    col: int
    cls: str                  # enclosing class (fields key by module::cls.attr)
    attr: str
    kind: str                 # "read" | "write" | "augwrite"
    under_locks: list[str]
    acq_lines: list[int]


@dataclasses.dataclass
class FunctionSummary:
    qualname: str             # "pkg.mod::Class.meth" / "pkg.mod::func"
    name: str
    relpath: str
    module: str
    line: int
    cls: str | None = None
    is_async: bool = False
    jit_entry: bool = False   # decorated @jax.jit/@shard_map/partial-thereof
    calls: list[CallSite] = dataclasses.field(default_factory=list)
    acquires: list[LockAcq] = dataclasses.field(default_factory=list)
    sync_ops: list[SyncOp] = dataclasses.field(default_factory=list)
    escapes: list[EscapeOp] = dataclasses.field(default_factory=list)
    # flow-insensitive local dataflow: var name -> union of source atoms
    assigns: dict[str, list[str]] = dataclasses.field(default_factory=dict)
    returns: list[str] = dataclasses.field(default_factory=list)
    params: list[str] = dataclasses.field(default_factory=list)
    attr_accesses: list[AttrAccess] = dataclasses.field(default_factory=list)
    # lines where `self` escapes this method (passed as an argument,
    # returned, stored, or a bound method handed out as a reference) — the
    # publish point the ownership phase keys __init__ immutability on
    self_escape_lines: list[int] = dataclasses.field(default_factory=list)
    # local receiver types the resolver can use: var -> "ClassName" (direct
    # construction) or "self.attr[]" (element pulled out of a typed
    # container field — `lq = self._queues[lane]`, `for lq in
    # self._queues.values()`). Flow-insensitive last-writer-wins is fine
    # here: a variable rebound across types just fails class lookup.
    local_types: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModuleSummary:
    module: str
    relpath: str
    imports: dict[str, str] = dataclasses.field(default_factory=dict)
    from_imports: dict[str, list[str]] = dataclasses.field(default_factory=dict)
    functions: dict[str, FunctionSummary] = dataclasses.field(default_factory=dict)
    classes: dict[str, dict[str, Any]] = dataclasses.field(default_factory=dict)
    aliases: dict[str, str] = dataclasses.field(default_factory=dict)
    lock_sites: dict[str, list[Any]] = dataclasses.field(default_factory=dict)
    # lock id -> [relpath, line] of the threading.Lock()/RLock() call, for
    # the runtime (lockcheck) edge cross-check
    disabled_lines: dict[str, list[str]] = dataclasses.field(default_factory=dict)
    file_disabled: list[str] = dataclasses.field(default_factory=list)
    parse_error: str | None = None

    # -- JSON round-trip (the cache format) --------------------------------
    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ModuleSummary":
        ms = cls(module=d["module"], relpath=d["relpath"],
                 imports=d["imports"], from_imports=d["from_imports"],
                 classes=d["classes"], aliases=d["aliases"],
                 lock_sites=d["lock_sites"],
                 disabled_lines=d["disabled_lines"],
                 file_disabled=d["file_disabled"],
                 parse_error=d.get("parse_error"))
        for qn, fd in d["functions"].items():
            fs = FunctionSummary(
                qualname=fd["qualname"], name=fd["name"],
                relpath=fd["relpath"], module=fd["module"], line=fd["line"],
                cls=fd["cls"], is_async=fd["is_async"],
                jit_entry=fd["jit_entry"],
                calls=[CallSite(**c) for c in fd["calls"]],
                acquires=[LockAcq(**a) for a in fd["acquires"]],
                sync_ops=[SyncOp(**s) for s in fd["sync_ops"]],
                escapes=[EscapeOp(**e) for e in fd["escapes"]],
                assigns=fd["assigns"], returns=fd["returns"],
                params=fd["params"],
                attr_accesses=[AttrAccess(**a)
                               for a in fd.get("attr_accesses", [])],
                self_escape_lines=fd.get("self_escape_lines", []),
                local_types=fd.get("local_types", {}))
            ms.functions[qn] = fs
        return ms


# --------------------------------------------------------------- extraction


def module_name_for(relpath: str) -> str:
    rp = relpath.replace("\\", "/")
    if rp.endswith("/__init__.py"):
        rp = rp[: -len("/__init__.py")]
    elif rp.endswith(".py"):
        rp = rp[:-3]
    return rp.replace("/", ".")


def _resolve_relative(module: str, level: int, target: str | None,
                      is_pkg: bool) -> str:
    """``from ..a import b`` inside ``module`` -> absolute dotted module.
    In a package ``__init__`` level 1 is the package itself; in a regular
    module it is the containing package (one component stripped)."""
    parts = module.split(".")
    strip = level - 1 if is_pkg else level
    base = parts[: len(parts) - strip] if strip <= len(parts) else []
    if target:
        base = base + target.split(".")
    return ".".join(base)


def _is_trace_decorator(dec: ast.expr) -> bool:
    name = dotted_name(dec)
    if name in _TRACE_WRAPPERS:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _TRACE_WRAPPERS:
            return True
        if fname in ("partial", "functools.partial") and dec.args:
            return dotted_name(dec.args[0]) in _TRACE_WRAPPERS
    return False


#: constructors whose instances act as locks in a ``with`` statement
_LOCK_CTORS = ("threading.Lock", "threading.RLock", "threading.Condition")


def _lock_expr_id(expr: ast.expr, module: str, cls: str | None,
                  cls_info: dict | None = None) -> str | None:
    """Normalized identity for a lock-ish with-context expression, or None
    if the expression is not lock-named. ``self._lock`` in class C ->
    ``module::C._lock``; module-global ``_LK`` -> ``module::_LK``; other
    receivers collapse to ``~attr`` (one global node per attribute name —
    ambiguous, but deterministic). ``cls_info`` (the extractor's per-class
    record) supplies lock ALIASES — ``self._lock = self._cond`` with
    ``self._cond = threading.Condition()`` makes both names one lock, and
    a Condition-named attribute (``_cv``) is lock-ish even though its name
    fails the regex."""
    name = terminal_name(expr)
    if not name:
        return None
    if isinstance(expr, ast.Attribute):
        recv = expr.value
        if isinstance(recv, ast.Name) and recv.id in ("self", "cls") and cls:
            if cls_info is not None:
                aliases = cls_info.get("lock_aliases", {})
                seen = set()
                while name in aliases and name not in seen:
                    seen.add(name)
                    name = aliases[name]
                if (name in cls_info.get("lock_attrs", [])
                        or _LOCK_NAME_RE.search(name)):
                    return f"{module}::{cls}.{name}"
                return None
            if _LOCK_NAME_RE.search(name):
                return f"{module}::{cls}.{name}"
            return None
        if _LOCK_NAME_RE.search(name):
            return f"~{name}"
        return None
    if _LOCK_NAME_RE.search(name):
        return f"{module}::{name}"
    return None


def _ctor_of(expr: ast.expr) -> str | None:
    """Dotted constructor name if ``expr`` is ``SomeClass(...)``."""
    if not isinstance(expr, ast.Call):
        return None
    name = dotted_name(expr.func)
    if name and name.split(".")[-1].lstrip("_")[:1].isupper():
        return name
    return None


def _attr_type_lookup(cinfo: dict, key: str) -> str | None:
    """attr_types lookup with ``attr[]`` keys routed to the container
    element-type table (attr_elem_types)."""
    if key.endswith("[]"):
        return cinfo.get("attr_elem_types", {}).get(key[:-2])
    return cinfo["attr_types"].get(key)


def _elem_ctor(value: ast.expr) -> str | None:
    """Homogeneous element-constructor type of a container expression:
    ``{k: C() for ...}`` / ``[C() for ...]`` / ``{k1: C(), k2: C()}`` /
    ``[C(), C()]`` all yield ``C``. Mixed or empty containers yield None —
    the element type must be total to be trusted."""
    if isinstance(value, ast.DictComp):
        return _ctor_of(value.value)
    if isinstance(value, (ast.ListComp, ast.SetComp)):
        return _ctor_of(value.elt)
    if isinstance(value, ast.Dict) and value.values:
        ctors = {_ctor_of(v) for v in value.values}
        return ctors.pop() if len(ctors) == 1 else None
    if isinstance(value, (ast.List, ast.Set)) and value.elts:
        ctors = {_ctor_of(e) for e in value.elts}
        return ctors.pop() if len(ctors) == 1 else None
    return None


class _Extractor(ast.NodeVisitor):
    """One pass over a module AST building the ModuleSummary."""

    def __init__(self, module: str, relpath: str) -> None:
        self.ms = ModuleSummary(module=module, relpath=relpath)
        self.is_pkg = relpath.replace("\\", "/").endswith("/__init__.py")

    # -- module structure --------------------------------------------------
    def extract(self, tree: ast.Module) -> ModuleSummary:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.ms.imports[a.asname or a.name.split(".", 1)[0]] = (
                        a.name if a.asname else a.name.split(".", 1)[0])
            elif isinstance(node, ast.ImportFrom):
                mod = (_resolve_relative(self.ms.module, node.level,
                                         node.module, self.is_pkg)
                       if node.level else (node.module or ""))
                for a in node.names:
                    self.ms.from_imports[a.asname or a.name] = [mod, a.name]
        self._extract_scope(tree.body, cls=None, prefix="")
        body_fn = FunctionSummary(
            qualname=f"{self.ms.module}::<module>", name="<module>",
            relpath=self.ms.relpath, module=self.ms.module, line=1)
        self._extract_body(tree.body, body_fn, cls=None, locks=[])
        self.ms.functions[body_fn.qualname] = body_fn
        return self.ms

    def _extract_scope(self, body: list[ast.stmt], cls: str | None,
                       prefix: str) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._extract_function(node, cls, prefix)
            elif isinstance(node, (ast.If, ast.Try)):
                # module-level conditional defs (version/feature gates)
                for sub_body in ([node.body] + [h.body for h in getattr(
                        node, "handlers", [])] + [getattr(node, "orelse", [])]
                        + [getattr(node, "finalbody", [])]):
                    self._extract_scope(sub_body, cls, prefix)
            elif isinstance(node, ast.ClassDef) and cls is None and not prefix:
                bases = [dotted_name(b) for b in node.bases if dotted_name(b)]
                info: dict[str, Any] = {"bases": bases, "methods": {},
                                        "attr_types": {}, "line": node.lineno,
                                        "attr_elem_types": {},
                                        "lock_attrs": [], "lock_aliases": {}}
                self.ms.classes[node.name] = info
                self._prescan_locks(node, info)
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        qn = f"{self.ms.module}::{node.name}.{sub.name}"
                        info["methods"][sub.name] = qn
                        self._extract_function(sub, node.name, "")
                self._infer_attr_types(node, info)
            elif isinstance(node, ast.Assign) and cls is None and not prefix:
                self._module_assign(node)

    def _prescan_locks(self, cnode: ast.ClassDef, info: dict) -> None:
        """Class-wide lock identity prescan, BEFORE any method body is
        walked: attributes constructed as Lock/RLock/Condition are
        lock-ish regardless of name (``self._cv``), and plain attribute
        aliases of them (``self._lock = self._cond``) or Condition
        wrappers (``threading.Condition(self._lock)``) collapse to ONE
        lock id — without this, code guarding one field through the
        condition and through its lock looks like two different locks."""
        for node in ast.walk(cnode):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            value = node.value
            if isinstance(value, ast.Call):
                if dotted_name(value.func) in _LOCK_CTORS:
                    if (value.args
                            and isinstance(value.args[0], ast.Attribute)
                            and isinstance(value.args[0].value, ast.Name)
                            and value.args[0].value.id == "self"):
                        # Condition(self._lock): same underlying lock
                        info["lock_aliases"][tgt.attr] = value.args[0].attr
                    elif tgt.attr not in info["lock_attrs"]:
                        info["lock_attrs"].append(tgt.attr)
            elif (isinstance(value, ast.Attribute)
                  and isinstance(value.value, ast.Name)
                  and value.value.id == "self"
                  and (value.attr in info["lock_attrs"]
                       or value.attr in info["lock_aliases"]
                       or _LOCK_NAME_RE.search(value.attr))):
                # self._lock = self._cond: one lock, two names
                info["lock_aliases"].setdefault(tgt.attr, value.attr)

    def _infer_attr_types(self, cnode: ast.ClassDef, info: dict) -> None:
        """self.x = ClassName(...) anywhere in the class body -> x:
        ClassName (a dotted constructor reference, resolved later); also
        ``self.x = self._meth()`` where ``_meth`` declares ``->
        ClassName`` — the factory-method idiom (``self._delta =
        self._fresh_delta()``) resolves through the return annotation."""
        ret_types: dict[str, str] = {}
        for sub in cnode.body:
            if (isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and sub.returns is not None):
                rname = dotted_name(sub.returns)
                if (rname
                        and rname.split(".")[-1].lstrip("_")[:1].isupper()):
                    ret_types[sub.name] = rname
        for node in ast.walk(cnode):
            if not isinstance(node, ast.Assign):
                continue
            # container-of-project-objects fields: every element the same
            # constructor makes the field's ELEMENT type known, so
            # `self._queues[lane].pop()` resolves through the subscript
            # (self.x = {k: C() for ...} / [C() for ...] / literal forms)
            elem = _elem_ctor(node.value)
            if elem is not None:
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        info["attr_elem_types"].setdefault(tgt.attr, elem)
                continue
            if not isinstance(node.value, ast.Call):
                continue
            ctor = dotted_name(node.value.func)
            if not ctor:
                continue
            if ctor.startswith("self.") and ctor.count(".") == 1:
                ctor = ret_types.get(ctor[len("self."):], "")
            if (not ctor
                    or not ctor.split(".")[-1].lstrip("_")[:1].isupper()):
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    info["attr_types"].setdefault(tgt.attr, ctor)

    def _module_assign(self, node: ast.Assign) -> None:
        """Module-level aliases worth resolving: ``g = f``,
        ``g = jax.jit(f)``, ``g = functools.partial(f, ...)``, plus lock
        construction sites (``_LK = threading.Lock()``)."""
        if len(node.targets) != 1 or not isinstance(node.targets[0], ast.Name):
            return
        target = node.targets[0].id
        value = node.value
        if isinstance(value, ast.Call):
            fname = dotted_name(value.func)
            if fname in _LOCK_CTORS:
                lock_id = f"{self.ms.module}::{target}"
                self.ms.lock_sites[lock_id] = [self.ms.relpath, node.lineno]
                return
            if fname in _TRACE_WRAPPERS or fname in ("partial",
                                                     "functools.partial"):
                if value.args:
                    inner = dotted_name(value.args[0])
                    if inner:
                        self.ms.aliases[target] = inner
                return
        name = dotted_name(value)
        if name:
            self.ms.aliases[target] = name

    # -- function bodies ---------------------------------------------------
    def _extract_function(self, node: ast.FunctionDef | ast.AsyncFunctionDef,
                          cls: str | None, prefix: str) -> None:
        qn = (f"{self.ms.module}::{cls}.{node.name}" if cls
              else f"{self.ms.module}::{prefix}{node.name}")
        # params EXCLUDE the receiver: param index i must line up with
        # explicit call-arg index i at bound-call sites (self._grab(x)
        # passes x at position 0), or every method-boundary taint/param
        # lookup in the solver is off by one — and `self` itself must not
        # read as "param 0 is a tracer" in jit-entry methods
        params = [a.arg for a in (node.args.posonlyargs + node.args.args)]
        if cls is not None and params and params[0] in ("self", "cls"):
            params = params[1:]
        fs = FunctionSummary(
            qualname=qn, name=node.name, relpath=self.ms.relpath,
            module=self.ms.module, line=node.lineno, cls=cls,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            jit_entry=any(_is_trace_decorator(d) for d in node.decorator_list),
            params=params)
        self.ms.functions[qn] = fs
        # lock-construction sites inside methods (self._lock = Lock())
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call)
                    and dotted_name(sub.value.func) in _LOCK_CTORS
                    and not sub.value.args):  # Condition(self._x) aliases
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self" and cls):
                        lock_id = f"{self.ms.module}::{cls}.{tgt.attr}"
                        self.ms.lock_sites[lock_id] = [self.ms.relpath,
                                                       sub.lineno]
        self._infer_local_types(node, fs)
        self._extract_body(node.body, fs, cls, locks=[])
        if cls is not None:
            self._compute_self_escapes(node, fs)
        # nested defs become their own functions, resolvable from the outer
        # scope by name ("outer.<locals>.inner")
        for sub in node.body:
            self._extract_nested(sub, cls, f"{prefix}{node.name}.<locals>."
                                 if not cls else f"{cls}.{node.name}.<locals>.")

    def _infer_local_types(self, node: ast.FunctionDef | ast.AsyncFunctionDef,
                           fs: FunctionSummary) -> None:
        """Pre-pass filling ``fs.local_types`` before call extraction, so a
        call through a typed local (``lq = self._queues[lane]; lq.push(r)``)
        resolves regardless of statement order. Nested defs are their own
        scopes and are skipped."""
        def self_attr(expr: ast.expr) -> str | None:
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return expr.attr
            return None

        def visit(body: list[ast.stmt]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)):
                    tgt = stmt.targets[0].id
                    ctor = _ctor_of(stmt.value)
                    if ctor is not None:
                        fs.local_types[tgt] = ctor
                    elif isinstance(stmt.value, ast.Subscript):
                        attr = self_attr(stmt.value.value)
                        if attr is not None:
                            fs.local_types[tgt] = f"self.{attr}[]"
                elif (isinstance(stmt, (ast.For, ast.AsyncFor))
                      and isinstance(stmt.target, ast.Name)):
                    it = stmt.iter
                    attr = self_attr(it)
                    if (attr is None and isinstance(it, ast.Call)
                            and isinstance(it.func, ast.Attribute)
                            and it.func.attr == "values" and not it.args):
                        attr = self_attr(it.func.value)
                    if attr is not None:
                        fs.local_types[stmt.target.id] = f"self.{attr}[]"
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        visit(sub)
                for h in getattr(stmt, "handlers", []):
                    visit(h.body)

        visit(node.body)

    def _compute_self_escapes(self, node: ast.FunctionDef
                              | ast.AsyncFunctionDef,
                              fs: FunctionSummary) -> None:
        """Lines where ``self`` leaves this method: any load of the bare
        name that is not an attribute receiver (argument positions,
        returns, container stores, comparisons — deliberately
        conservative), plus bound-method references handed out
        (``Thread(target=self._loop)`` publishes ``self`` to the thread)."""
        recv_ids: set[int] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and isinstance(sub.value,
                                                             ast.Name):
                recv_ids.add(id(sub.value))
        esc: set[int] = set()
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Name) and sub.id == "self"
                    and isinstance(sub.ctx, ast.Load)
                    and id(sub) not in recv_ids):
                esc.add(sub.lineno)
        for cs in fs.calls:
            # a BOUND METHOD handed to a spawn/callback sink
            # (Thread(target=self._loop)) publishes self to that thread;
            # one stored in a plain constructor does not count — the
            # receiving object cannot run it until something ELSE spawns,
            # and that spawn is its own escape. self.a.b publishes the
            # FIELD object a, not self.
            if (cs.is_ref and cs.name.startswith("self.")
                    and cs.name.count(".") == 1
                    and cs.ref_of.split(".")[-1] in _CALLBACK_SINKS):
                esc.add(cs.line)
        fs.self_escape_lines = sorted(esc)

    def _extract_nested(self, node: ast.stmt, cls: str | None,
                        prefix: str) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{self.ms.module}::{prefix}{sub.name}"
                if qn not in self.ms.functions:
                    fs = FunctionSummary(
                        qualname=qn, name=sub.name, relpath=self.ms.relpath,
                        module=self.ms.module, line=sub.lineno, cls=None,
                        is_async=isinstance(sub, ast.AsyncFunctionDef),
                        jit_entry=any(_is_trace_decorator(d)
                                      for d in sub.decorator_list),
                        params=[a.arg for a in (sub.args.posonlyargs
                                                + sub.args.args)])
                    self.ms.functions[qn] = fs
                    self._extract_body(sub.body, fs, cls, locks=[])

    # taint atoms ----------------------------------------------------------
    def _atoms(self, expr: ast.expr, fs: FunctionSummary) -> list[str]:
        """Taint atoms of ``expr``: 'dev' (definitely a device value),
        'param:<i>', 'var:<name>', 'call:<idx>' (the idx-th call site's
        result). Flow-insensitive; resolution happens in contexts.py."""
        out: set[str] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute):
                if node.attr in _UNTAINT_ATTRS:
                    continue
                if node.attr.endswith("_dev"):
                    out.add("dev")
            elif isinstance(node, ast.Name):
                if node.id.endswith("_dev"):
                    out.add("dev")
                elif node.id in fs.params:
                    out.add(f"param:{fs.params.index(node.id)}")
                else:
                    out.add(f"var:{node.id}")
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func)
                root = name.split(".", 1)[0]
                if root in ("jnp",) or name.startswith("jax.numpy."):
                    out.add("dev")
                elif name == "jax.device_put":
                    out.add("dev")
                elif name:
                    out.add(f"callname:{name}:{node.lineno}")
        return sorted(out)

    def _extract_body(self, body: list[ast.stmt], fs: FunctionSummary,
                      cls: str | None,
                      locks: list[tuple[str, int]]) -> None:
        """Walk statements in ``fs``'s own execution scope, tracking the
        lexical lock stack as (lock id, with-statement line) pairs; nested
        defs/lambdas are boundaries (their code runs later, under
        different conditions)."""
        for stmt in body:
            self._extract_stmt(stmt, fs, cls, locks)

    def _extract_stmt(self, node: ast.AST, fs: FunctionSummary,
                      cls: str | None,
                      locks: list[tuple[str, int]]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # boundary: handled by _extract_nested / _extract_scope
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_locks = list(locks)
            for item in node.items:
                lock_id = _lock_expr_id(
                    item.context_expr, self.ms.module, cls,
                    self.ms.classes.get(cls) if cls else None)
                # the context expression itself evaluates under the OUTER set
                self._extract_expr(item.context_expr, fs, locks, cls)
                if lock_id is not None:
                    fs.acquires.append(LockAcq(
                        lock_id=lock_id, line=node.lineno,
                        under_locks=[l for l, _ in new_locks]))
                    new_locks.append((lock_id, node.lineno))
            for sub in node.body:
                self._extract_stmt(sub, fs, cls, new_locks)
            return
        if isinstance(node, ast.AugAssign):
            # self.x += 1 is a read-modify-write in ONE record (the racy
            # increment shape); the value expression still walks normally
            tgt = node.target
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in ("self", "cls") and cls):
                fs.attr_accesses.append(AttrAccess(
                    line=tgt.lineno, col=tgt.col_offset, cls=cls,
                    attr=tgt.attr, kind="augwrite",
                    under_locks=[l for l, _ in locks],
                    acq_lines=[ln for _, ln in locks]))
                self._extract_expr(node.value, fs, locks, cls)
                return
        if isinstance(node, ast.Assign):
            atoms = self._atoms(node.value, fs)

            def bind(tgt: ast.expr) -> None:
                # only NAME bindings take the value's taint — an attribute
                # or subscript store (self._mirror = <dev>) must not taint
                # the receiver (`self`), or one device-valued attr store
                # poisons every later use of the object
                if isinstance(tgt, ast.Name):
                    fs.assigns.setdefault(tgt.id, [])
                    fs.assigns[tgt.id] = sorted(
                        set(fs.assigns[tgt.id]) | set(atoms))
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    for el in tgt.elts:
                        bind(el)
                elif isinstance(tgt, ast.Starred):
                    bind(tgt.value)

            for t in node.targets:
                bind(t)
        elif isinstance(node, ast.Return) and node.value is not None:
            fs.returns = sorted(set(fs.returns)
                                | set(self._atoms(node.value, fs)))
        # expressions inside this statement (calls, sync ops, escapes);
        # non-stmt non-expr children (except handlers, withitems, etc.)
        # recurse generically so their bodies keep the lock stack
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.expr):
                self._extract_expr(child, fs, locks, cls)
            else:
                self._extract_stmt(child, fs, cls, locks)

    def _extract_expr(self, expr: ast.expr, fs: FunctionSummary,
                      locks: list[tuple[str, int]],
                      cls: str | None = None) -> None:
        lock_ids = [l for l, _ in locks]
        acq_lines = [ln for _, ln in locks]
        # lambda bodies execute later — prune them from this walk
        in_lambda: set[int] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                for sub in ast.walk(node):
                    if sub is not node:
                        in_lambda.add(id(sub))
        # method-call receivers: `self._refresh()` is a CALL record, not a
        # field read of `_refresh` (the attr_accesses table is fields only)
        call_funcs: set[int] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and isinstance(node.func,
                                                         ast.Attribute):
                recv = node.func.value
                if isinstance(recv, ast.Name) and recv.id in ("self", "cls"):
                    call_funcs.add(id(node.func))
        if cls:
            for node in ast.walk(expr):
                if id(node) in in_lambda or id(node) in call_funcs:
                    continue
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in ("self", "cls")):
                    kind = ("write" if isinstance(node.ctx, (ast.Store,
                                                             ast.Del))
                            else "read")
                    fs.attr_accesses.append(AttrAccess(
                        line=node.lineno, col=node.col_offset, cls=cls,
                        attr=node.attr, kind=kind,
                        under_locks=list(lock_ids),
                        acq_lines=list(acq_lines)))
        for node in ast.walk(expr):
            if id(node) in in_lambda or not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if isinstance(node.func, ast.Attribute) and not name:
                recv = node.func.value
                if (isinstance(recv, ast.Subscript)
                        and isinstance(recv.value, ast.Attribute)
                        and isinstance(recv.value.value, ast.Name)
                        and recv.value.value.id == "self"):
                    # self.attr[key].meth(...): resolvable when the field's
                    # element type is known (attr_elem_types)
                    name = f"self.{recv.value.attr}[].{node.func.attr}"
                else:
                    # chained receiver we cannot express as a dotted name
                    name = f"?.{node.func.attr}"
            if not name:
                continue
            arg_atoms = {}
            for i, a in enumerate(node.args):
                atoms = self._atoms(a, fs)
                if atoms:
                    arg_atoms[str(i)] = atoms
            fs.calls.append(CallSite(
                line=node.lineno, col=node.col_offset, name=name,
                under_locks=list(lock_ids), arg_atoms=arg_atoms))
            # bare project-function references passed as arguments (executor
            # thunks, shard_map wrapping, Thread targets): recorded as refs
            seen_refs: set[tuple[int, str]] = set()
            for a in (*node.args, *(kw.value for kw in node.keywords)):
                rname = dotted_name(a)
                if rname and not rname[:1].isupper():
                    seen_refs.add((getattr(a, "lineno", node.lineno), rname))
                    fs.calls.append(CallSite(
                        line=getattr(a, "lineno", node.lineno),
                        col=getattr(a, "col_offset", 0), name=rname,
                        under_locks=list(lock_ids), is_ref=True, ref_of=name))
                elif (isinstance(a, ast.Call)
                      and dotted_name(a.func) in ("partial",
                                                  "functools.partial")
                      and a.args):
                    pname = dotted_name(a.args[0])
                    if pname:
                        seen_refs.add((a.lineno, pname))
                        fs.calls.append(CallSite(
                            line=a.lineno, col=a.col_offset, name=pname,
                            under_locks=list(lock_ids), is_ref=True,
                            ref_of=name))
            if terminal_name(node.func) in _CALLBACK_SINKS:
                # thread-escape sinks get a DEEP argument walk so a target
                # wrapped one level (`Thread(target=crash_guard(self._loop))`)
                # still registers as escaping to another thread; call
                # receivers are skipped (they are calls, not references)
                sink_func_ids = {id(n.func) for n in ast.walk(node)
                                 if isinstance(n, ast.Call)}
                for a in (*node.args, *(kw.value for kw in node.keywords)):
                    recv_ids = {id(n.value) for n in ast.walk(a)
                                if isinstance(n, ast.Attribute)}
                    for sub in ast.walk(a):
                        if (id(sub) in in_lambda or id(sub) in sink_func_ids
                                or id(sub) in recv_ids):
                            continue
                        if not isinstance(sub, (ast.Name, ast.Attribute)):
                            continue
                        rname = dotted_name(sub)
                        if (not rname or rname[:1].isupper()
                                or rname in ("self", "cls")
                                or (sub.lineno, rname) in seen_refs):
                            continue
                        seen_refs.add((sub.lineno, rname))
                        fs.calls.append(CallSite(
                            line=sub.lineno, col=sub.col_offset, name=rname,
                            under_locks=list(lock_ids), is_ref=True,
                            ref_of=name))
            # host-sync ops / escapes
            tail = terminal_name(node.func)
            operand_atoms: list[str] = []
            if node.args:
                operand_atoms = self._atoms(node.args[0], fs)
            if tail == "block_until_ready" and isinstance(node.func,
                                                          ast.Attribute):
                recv_atoms = self._atoms(node.func.value, fs)
                fs.sync_ops.append(SyncOp(line=node.lineno,
                                          op="block_until_ready",
                                          atoms=recv_atoms,
                                          under_locks=list(lock_ids)))
            elif name in ("jax.device_get", "device_get"):
                fs.sync_ops.append(SyncOp(line=node.lineno, op="device_get",
                                          atoms=operand_atoms,
                                          under_locks=list(lock_ids)))
                # device_get's operand is a device array BY CONTRACT —
                # the escape is definite no matter where the value came from
                fs.escapes.append(EscapeOp(line=node.lineno,
                                           conv="jax.device_get",
                                           atoms=["dev"]))
            elif (tail in _HOST_CONV_METHODS
                  and isinstance(node.func, ast.Attribute)):
                recv_atoms = self._atoms(node.func.value, fs)
                fs.sync_ops.append(SyncOp(line=node.lineno, op=tail,
                                          atoms=recv_atoms,
                                          under_locks=list(lock_ids)))
                fs.escapes.append(EscapeOp(line=node.lineno, conv=f".{tail}",
                                           atoms=recv_atoms))
            elif name in _HOST_CONV_NAMES:
                fs.sync_ops.append(SyncOp(line=node.lineno, op=name,
                                          atoms=operand_atoms,
                                          under_locks=list(lock_ids)))
                fs.escapes.append(EscapeOp(line=node.lineno, conv=name,
                                           atoms=operand_atoms))


def _suppression_maps(src: str) -> tuple[dict[str, list[str]], list[str]]:
    """(line -> rules suppressed for findings ON that line, file-level
    rules). A finding on line N is covered by a pragma on N itself or on a
    pure comment line N-1 (the deep tier does not honor with/def-header
    pragmas — a chain finding has no single enclosing block)."""
    lines = src.splitlines()
    per_line: dict[str, list[str]] = {}
    file_off: list[str] = []
    for i, line in enumerate(lines[:20]):
        m = _DISABLE_FILE_RE.search(line)
        if m:
            file_off.extend(r.strip() for r in m.group(1).split(",")
                            if r.strip())
    for i, line in enumerate(lines, start=1):
        m = _DISABLE_RE.search(line)
        if not m:
            continue
        rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
        per_line.setdefault(str(i), []).extend(rules)
        # a pragma on a pure comment line covers the line below
        if line.lstrip().startswith("#"):
            per_line.setdefault(str(i + 1), []).extend(rules)
    return per_line, file_off


def extract_module(src: str, relpath: str,
                   module: str | None = None) -> ModuleSummary:
    """Phase 1: the cacheable per-file summary (pure in ``src``)."""
    module = module or module_name_for(relpath)
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        ms = ModuleSummary(module=module, relpath=relpath,
                           parse_error=f"{e.msg} (line {e.lineno})")
        return ms
    ms = _Extractor(module, relpath).extract(tree)
    ms.disabled_lines, ms.file_disabled = _suppression_maps(src)
    return ms


# --------------------------------------------------------------- resolution


@dataclasses.dataclass
class GraphStats:
    files: int = 0
    functions: int = 0
    resolved_calls: int = 0
    unresolved_calls: int = 0
    fn_refs: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class ProjectGraph:
    """The resolved whole-program view over a set of ModuleSummaries."""

    def __init__(self, summaries: Iterable[ModuleSummary]) -> None:
        self.modules: dict[str, ModuleSummary] = {}
        self.functions: dict[str, FunctionSummary] = {}
        self.stats = GraphStats()
        for ms in summaries:
            self.modules[ms.module] = ms
            for qn, fs in ms.functions.items():
                self.functions[qn] = fs
        self.stats.files = len(self.modules)
        self.stats.functions = len(self.functions)
        # method name -> defining class qualnames (unique-name fallback)
        self._methods_by_name: dict[str, list[str]] = {}
        for ms in self.modules.values():
            for cname, cinfo in ms.classes.items():
                for mname, qn in cinfo["methods"].items():
                    self._methods_by_name.setdefault(mname, []).append(qn)
        # callee edges: qualname -> list[(CallSite, [callee qualnames])]
        self.calls: dict[str, list[tuple[CallSite, list[str]]]] = {}
        self.callers: dict[str, set[str]] = {}
        self._resolve_all()
        # lock construction site index (for the lockcheck cross-check)
        self.lock_sites: dict[str, tuple[str, int]] = {}
        for ms in self.modules.values():
            for lock_id, (rp, line) in ms.lock_sites.items():
                self.lock_sites[lock_id] = (rp, line)

    # -- name resolution ---------------------------------------------------
    def _resolve_all(self) -> None:
        for qn, fs in self.functions.items():
            resolved_list: list[tuple[CallSite, list[str]]] = []
            for cs in fs.calls:
                raw = self._resolve_call(fs, cs)
                targets = [t for t in raw if t in self.functions]
                resolved_list.append((cs, targets))
                if cs.is_ref:
                    self.stats.fn_refs += 1
                elif raw:
                    self.stats.resolved_calls += 1
                elif self._counts_as_unresolved(cs.name):
                    self.stats.unresolved_calls += 1
                for t in targets:
                    self.callers.setdefault(t, set()).add(qn)
            self.calls[qn] = resolved_list

    @staticmethod
    def _counts_as_unresolved(name: str) -> bool:
        """Only attribute calls and non-builtin names count as analysis
        blindness; ``len()``/``jnp.where()`` are not project calls."""
        root = name.split(".", 1)[0].lstrip("?")
        return root not in _KNOWN_EXTERNAL_ROOTS

    def _project_module(self, dotted: str) -> ModuleSummary | None:
        """The summary for dotted module ``a.b.c``, trying package
        __init__ resolution (a.b.c may be a name inside package a.b)."""
        return self.modules.get(dotted)

    def _lookup_in_module(self, mod: str, attr: str,
                          _seen: set[tuple[str, str]] | None = None
                          ) -> list[str]:
        seen = _seen if _seen is not None else set()
        if (mod, attr) in seen:  # re-export cycles (pkg __init__ fan-outs)
            return []
        seen.add((mod, attr))
        ms = self._project_module(mod)
        if ms is None:
            return []
        qn = f"{mod}::{attr}"
        if qn in ms.functions:
            return [qn]
        if attr in ms.aliases:
            return self._resolve_dotted(ms, ms.aliases[attr])
        if attr in ms.classes:
            init = ms.classes[attr]["methods"].get("__init__")
            # a project class without __init__ (dataclasses, exceptions) is
            # KNOWN — resolved to a bodiless constructor, not a blind spot
            return [init] if init else ["<ctor>"]
        if attr in ms.from_imports:
            m2, a2 = ms.from_imports[attr]
            return self._lookup_in_module(m2, a2, seen)
        return []

    def _resolve_dotted(self, ms: ModuleSummary, name: str,
                        cls: str | None = None,
                        fs: FunctionSummary | None = None) -> list[str]:
        """Resolve a dotted expression name to project function qualnames."""
        parts = name.split(".")
        head = parts[0]

        # self.method(...) / self.attr.method(...)
        if head == "self" and cls is not None:
            return self._resolve_self_chain(ms, cls, parts[1:])

        # typed local receiver: `lq = self._queues[lane]; lq.push(r)` or
        # `q = Worker(); q.start_all()` — the local binding shadows module
        # scope, so this is checked before the module-name paths (falls
        # back to the unique-method heuristic in _resolve_call on a miss)
        if fs is not None and head in fs.local_types and len(parts) >= 2:
            ltype = fs.local_types[head]
            if ltype.startswith("self.") and cls is not None:
                return self._resolve_self_chain(
                    ms, cls, ltype[len("self."):].split(".") + parts[1:])
            found = self._class_info(ms, ltype)
            if found is not None and len(parts) == 2:
                f_ms, f_info = found
                for cname, ci in f_ms.classes.items():
                    if ci is f_info:
                        return self._method_on_class(f_ms, cname, parts[1])
            return []

        # plain module-scope name (local aliases are covered by the
        # fn-ref CallSites the extractor records at the aliasing call)
        if len(parts) == 1:
            return self._lookup_in_module(ms.module, head)

        # imported module attribute: mod.f(...) / pkg.sub.f(...)
        if head in ms.imports:
            mod = ms.imports[head]
            target_mod = ".".join([mod] + parts[1:-1])
            return self._lookup_in_module(target_mod, parts[-1])
        # from-imported object with attribute: obj.method(...)
        if head in ms.from_imports:
            m2, a2 = ms.from_imports[head]
            ms2 = self._project_module(m2)
            if ms2 is not None and a2 in ms2.classes and len(parts) == 2:
                qn = ms2.classes[a2]["methods"].get(parts[1])
                return [qn] if qn else []
            if len(parts) >= 2:
                return self._lookup_in_module(f"{m2}.{a2}"
                                              if self._project_module(f"{m2}.{a2}")
                                              else m2, parts[-1])
        # ClassName.method(...) in the same module
        if head in ms.classes and len(parts) == 2:
            qn = ms.classes[head]["methods"].get(parts[1])
            return [qn] if qn else []
        return []

    def _class_info(self, ms: ModuleSummary,
                    cls_ref: str) -> tuple[ModuleSummary, dict] | None:
        """Find the class info for a (possibly imported) class reference."""
        parts = cls_ref.split(".")
        if parts[0] in ms.classes and len(parts) == 1:
            return ms, ms.classes[parts[0]]
        if parts[0] in ms.from_imports:
            m2, a2 = ms.from_imports[parts[0]]
            ms2 = self._project_module(m2)
            if ms2 is not None and a2 in ms2.classes:
                return ms2, ms2.classes[a2]
        if parts[0] in ms.imports and len(parts) >= 2:
            mod = ".".join([ms.imports[parts[0]]] + parts[1:-1])
            ms2 = self._project_module(mod)
            if ms2 is not None and parts[-1] in ms2.classes:
                return ms2, ms2.classes[parts[-1]]
        return None

    def _method_on_class(self, ms: ModuleSummary, cls: str,
                         meth: str) -> list[str]:
        """Method lookup with a best-effort project MRO walk."""
        seen: set[str] = set()
        queue: list[tuple[ModuleSummary, str]] = [(ms, cls)]
        while queue:
            cur_ms, cur_cls = queue.pop(0)
            key = f"{cur_ms.module}::{cur_cls}"
            if key in seen:
                continue
            seen.add(key)
            cinfo = cur_ms.classes.get(cur_cls)
            if cinfo is None:
                continue
            qn = cinfo["methods"].get(meth)
            if qn:
                return [qn]
            for base in cinfo["bases"]:
                found = self._class_info(cur_ms, base)
                if found:
                    base_ms, base_info = found
                    # recover the class NAME for the queue
                    for bname, binfo in base_ms.classes.items():
                        if binfo is base_info:
                            queue.append((base_ms, bname))
                            break
        return []

    def _resolve_self_chain(self, ms: ModuleSummary, cls: str,
                            rest: list[str]) -> list[str]:
        """self.a.b.meth(...) via inferred attribute types."""
        if not rest:
            return []
        if len(rest) == 1:
            return self._method_on_class(ms, cls, rest[0])
        cinfo = ms.classes.get(cls)
        cur = _attr_type_lookup(cinfo, rest[0]) if cinfo else None
        cur_ms = ms
        for hop in rest[1:-1]:
            if cur is None:
                return []
            found = self._class_info(cur_ms, cur)
            if not found:
                return []
            cur_ms, cinfo2 = found
            cur = _attr_type_lookup(cinfo2, hop)
        if cur is None:
            return []
        found = self._class_info(cur_ms, cur)
        if not found:
            return []
        final_ms, final_info = found
        for cname, cinfo3 in final_ms.classes.items():
            if cinfo3 is final_info:
                return self._method_on_class(final_ms, cname, rest[-1])
        return []

    def _resolve_call(self, fs: FunctionSummary, cs: CallSite) -> list[str]:
        ms = self.modules[fs.module]
        name = cs.name
        if name.startswith("?."):
            # chained receiver: fall back to unique method name
            return self._unique_method(name[2:])
        # nested function in the same enclosing def
        if "." not in name:
            host = fs.qualname.rsplit("::", 1)[-1]
            nested = f"{fs.module}::{host}.<locals>.{name}"
            if nested in self.functions:
                return [nested]
        targets = self._resolve_dotted(ms, name, cls=fs.cls, fs=fs)
        if targets:
            return targets
        # obj.method(...) where the method name is uniquely project-defined
        if "." in name:
            return self._unique_method(name.split(".")[-1])
        return []

    #: ubiquitous builtin-container/primitive method names excluded from
    #: the unique-method fallback: `self._buf.extend(...)` on a plain list
    #: must not resolve to the one project class that happens to define
    #: `extend` — those calls are counted unresolved (honest blindness)
    #: unless the receiver's type is actually inferred
    _BUILTIN_METHOD_NAMES = frozenset({
        "append", "extend", "insert", "remove", "pop", "popleft",
        "appendleft", "clear", "update", "get", "put", "add", "discard",
        "items", "keys", "values", "copy", "sort", "reverse", "count",
        "index", "setdefault", "get_nowait", "put_nowait", "qsize",
        "empty", "full", "task_done", "join", "split", "strip", "encode",
        "decode", "format", "read", "write", "readline", "flush", "seek",
        "close", "set", "is_set", "wait", "acquire", "release", "notify",
        "notify_all", "locked", "start", "result", "cancel", "done",
    })

    def _unique_method(self, meth: str) -> list[str]:
        if meth in self._BUILTIN_METHOD_NAMES:
            return []
        cands = self._methods_by_name.get(meth, [])
        if len(cands) == 1 and cands[0] in self.functions:
            return [cands[0]]
        return []
