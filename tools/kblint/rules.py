"""The project-invariant rules. Importing this module populates RULES."""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from .core import Rule, register


def dotted_name(node: ast.expr) -> str:
    """``a.b.c`` for Name/Attribute chains, "" for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def terminal_name(node: ast.expr) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def walk_same_scope(body: list[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class defs —
    code inside a nested def runs later, under different conditions (e.g.
    an executor thunk defined in a coroutine, or a callback defined under a
    lock)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            yield node  # the def statement itself, but not its contents
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# Module-level callables that block the calling thread. Method calls on
# arbitrary objects (sock.recv, proc.wait) are untypeable statically and are
# the runtime lock-order detector's job (util/lockcheck.py).
_BLOCKING_CALLS = {
    "time.sleep",
    "socket.create_connection",
    "urllib.request.urlopen",
    "open",
}
_BLOCKING_MODULES = ("subprocess", "requests")


def _is_blocking_call(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name in _BLOCKING_CALLS:
        return name
    root = name.split(".", 1)[0]
    if root in _BLOCKING_MODULES:
        return name
    return None


@register
class NoBlockingInAsync(Rule):
    """An event-loop thread serves every watch stream on the port; one
    blocking call stalls them all. Blocking work belongs in
    ``run_in_executor``."""

    rule_id = "KB101"
    summary = "no blocking calls inside async def bodies (endpoint/, server/)"

    def applies(self, relpath: str) -> bool:
        return relpath.replace("\\", "/").startswith(
            ("kubebrain_tpu/endpoint/", "kubebrain_tpu/server/")
        )

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            for inner in walk_same_scope(node.body):
                # nested async defs are visited by the outer ast.walk
                if isinstance(inner, ast.AsyncFunctionDef):
                    continue
                if isinstance(inner, ast.Call):
                    name = _is_blocking_call(inner)
                    if name:
                        yield inner, (
                            f"blocking call {name}() inside async def "
                            f"{node.name!r}; use run_in_executor"
                        )


_LOCK_NAME_RE = re.compile(r"lock$", re.IGNORECASE)


def _lock_expr(item: ast.withitem) -> str | None:
    name = terminal_name(item.context_expr)
    if name and _LOCK_NAME_RE.search(name):
        return dotted_name(item.context_expr) or name
    return None


@register
class NoDispatchUnderLock(Rule):
    """JAX dispatch can block on device availability and RPC/sleep on the
    network; either inside a ``threading.Lock`` region turns one slow call
    into a process-wide convoy (and, cross-lock, a deadlock)."""

    rule_id = "KB102"
    summary = "no JAX dispatch, RPC, or sleeps while holding a threading lock"

    def applies(self, relpath: str) -> bool:
        return relpath.replace("\\", "/").startswith("kubebrain_tpu/")

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            locks = [l for l in (_lock_expr(i) for i in node.items) if l]
            if not locks:
                continue
            held = locks[0]
            for inner in walk_same_scope(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                name = dotted_name(inner.func)
                if name.startswith("jax."):
                    yield inner, f"JAX dispatch {name}() while holding {held}"
                elif terminal_name(inner.func) == "block_until_ready":
                    yield inner, f"block_until_ready() while holding {held}"
                elif _is_blocking_call(inner):
                    yield inner, f"blocking call {name}() while holding {held}"


@register
class NoBareExcept(Rule):
    """A bare ``except:`` swallows KeyboardInterrupt/SystemExit and hides
    sequencer thread death as silent data loss."""

    rule_id = "KB103"
    summary = "no bare except clauses"

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield node, "bare except: name the exceptions (or use Exception)"


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _is_jit_decorator(dec: ast.expr) -> bool:
    if dotted_name(dec) in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_NAMES:
            return True  # @jax.jit(static_argnums=...)
        if fname in ("partial", "functools.partial") and dec.args:
            return dotted_name(dec.args[0]) in _JIT_NAMES
    return False


@register
class NoHostSyncInJit(Rule):
    """``device_get``/``block_until_ready`` inside a jitted kernel breaks
    tracing purity: it either fails under jit or silently forces a host
    sync per dispatch, destroying the scan kernel's pipelining."""

    rule_id = "KB104"
    summary = "no jax.device_get / block_until_ready inside @jax.jit kernels (ops/)"

    def applies(self, relpath: str) -> bool:
        return relpath.replace("\\", "/").startswith("kubebrain_tpu/ops/")

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(_is_jit_decorator(d) for d in node.decorator_list):
                continue
            for inner in walk_same_scope(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                name = dotted_name(inner.func)
                if name in ("jax.device_get", "device_get"):
                    yield inner, f"host sync {name}() inside jitted {node.name!r}"
                elif terminal_name(inner.func) == "block_until_ready":
                    yield inner, f"block_until_ready() inside jitted {node.name!r}"


_TIME_TIME_MODULES = re.compile(r"^_?time$")


def _is_time_time(call: ast.Call) -> bool:
    """``time.time()`` (including aliased imports like ``_time.time()``)."""
    func = call.func
    return (
        isinstance(func, ast.Attribute)
        and func.attr == "time"
        and isinstance(func.value, ast.Name)
        and bool(_TIME_TIME_MODULES.match(func.value.id))
    )


def _contains_time_time(expr: ast.expr) -> bool:
    return any(
        isinstance(n, ast.Call) and _is_time_time(n) for n in ast.walk(expr)
    )


@register
class NoPrintOrRawLatency(Rule):
    """Serving-path observability goes through the tracer/metrics facade:
    ``print()`` writes to a stdout nobody scrapes (and blocks on a full
    pipe), and hand-rolled ``time.time() - t0`` latency math measures wall
    clock (jumps on NTP steps) and is invisible to /metrics and
    /debug/traces. Use ``trace.TRACER.stage(...)``/``record_stage`` or
    ``metrics.timed(...)``/``emit_histogram``."""

    rule_id = "KB107"
    summary = ("no print() and no raw time.time() latency measurement in "
               "server/, sched/, endpoint/ — use trace/metrics helpers")

    def applies(self, relpath: str) -> bool:
        return relpath.replace("\\", "/").startswith(
            ("kubebrain_tpu/server/", "kubebrain_tpu/sched/",
             "kubebrain_tpu/endpoint/")
        )

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id == "print":
                yield node, ("print() on the serving path; use logging or "
                             "the metrics/trace facade")
            elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                if _contains_time_time(node.left) or _contains_time_time(node.right):
                    yield node, (
                        "raw time.time() latency measurement; use "
                        "trace.TRACER.stage()/metrics.timed() (monotonic, "
                        "lands on /metrics and /debug/traces)"
                    )


_TTL_TOKENS = {"ttl", "deadline", "deadlines", "expire", "expires", "expired",
               "expiry", "lease", "leases", "keepalive"}


def _ttlish(expr: ast.expr) -> str | None:
    """The dotted name of the first TTL/deadline-carrying Name/Attribute
    inside ``expr`` ('ttl', 'deadline', 'lease.expires_at', ...)."""
    for node in ast.walk(expr):
        name = terminal_name(node) if isinstance(node, (ast.Name, ast.Attribute)) else ""
        if name and _TTL_TOKENS & set(name.lower().split("_")):
            return dotted_name(node) or name
    return None


@register
class MonotonicLeaseClock(Rule):
    """Wall-clock TTL math breaks under clock steps: an NTP jump (or VM
    suspend/resume) either mass-expires every lease or grants them hours of
    free life. Live deadlines belong on the monotonic clock —
    ``kubebrain_tpu/lease/clock.py`` is the one serving-path module allowed
    to touch the conversion."""

    rule_id = "KB108"
    summary = ("no time.time() TTL/deadline arithmetic on the serving path "
               "outside kubebrain_tpu/lease/clock.py — use lease.clock")

    def applies(self, relpath: str) -> bool:
        rp = relpath.replace("\\", "/")
        if rp == "kubebrain_tpu/lease/clock.py":
            return False
        return rp.startswith((
            "kubebrain_tpu/lease/", "kubebrain_tpu/backend/",
            "kubebrain_tpu/server/", "kubebrain_tpu/sched/",
            "kubebrain_tpu/endpoint/",
        ))

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Sub)):
                sides = (node.left, node.right)
                if any(_contains_time_time(s) for s in sides):
                    name = _ttlish(node.left) or _ttlish(node.right)
                    if name:
                        yield node, (
                            f"wall-clock TTL/deadline arithmetic with {name!r}; "
                            "use kubebrain_tpu.lease.clock (monotonic)"
                        )
            elif isinstance(node, ast.Compare):
                exprs = (node.left, *node.comparators)
                if any(_contains_time_time(e) for e in exprs):
                    name = next((t for e in exprs if (t := _ttlish(e))), None)
                    if name:
                        yield node, (
                            f"wall-clock deadline comparison with {name!r}; "
                            "use kubebrain_tpu.lease.clock (monotonic)"
                        )
            elif isinstance(node, ast.Assign):
                # deadline = time.time() + 30 — ttl-ish target, constant rhs
                value = node.value
                if not (isinstance(value, ast.BinOp)
                        and isinstance(value.op, (ast.Add, ast.Sub))
                        and _contains_time_time(value)):
                    continue
                if _ttlish(value.left) or _ttlish(value.right):
                    continue  # the BinOp branch reports this one
                for target in node.targets:
                    name = _ttlish(target) if isinstance(
                        target, (ast.Name, ast.Attribute)) else None
                    if name:
                        yield node, (
                            f"wall-clock deadline assigned to {name!r}; "
                            "use kubebrain_tpu.lease.clock (monotonic)"
                        )
                        break


#: the device scan-kernel entry points (ops.scan_pallas + the engine's jit
#: wrappers). Launching one anywhere except the engine's assembly points
#: forks the query-packing logic: a stray call site can silently disagree
#: with `_dev_mask`/`_dev_mask_batch` on bound canonicalization, revision
#: splitting, pow2 padding, or the kernel/mesh selection — exactly the
#: drift the single-assembly-point discipline exists to prevent.
_SCAN_DISPATCH_NAMES = {
    "scan_mask_pallas", "scan_mask_pallas_q",
    "visibility_mask_batch", "visibility_mask_batch_cached",
    "visibility_mask_batch_cached_q",
    "_vis_batch", "_vis_batch_q", "_vis_batch_pallas", "_vis_batch_pallas_q",
}
#: functions allowed to reference them: the two engine assembly points and
#: the module-level jit wrappers those assembly points dispatch through
_SCAN_DISPATCH_ALLOWED = {
    "_dev_mask", "_dev_mask_batch",
    "_vis_batch", "_vis_batch_q", "_vis_batch_pallas", "_vis_batch_pallas_q",
}


@register
class ScanDispatchOnlyInAssemblyPoints(Rule):
    """Device scan dispatch in the scheduler/TPU-engine layers may only
    happen inside the `_dev_mask`/`_dev_mask_batch` assembly points (and
    the engine's own jit wrappers they call) — stray
    `scan_mask_pallas`/`visibility_mask_batch` call sites bypass the one
    place query packing, Q padding, and kernel selection are kept
    coherent."""

    rule_id = "KB109"
    summary = ("device scan kernels may only be dispatched from the "
               "_dev_mask/_dev_mask_batch assembly points "
               "(sched/, storage/tpu/)")

    def applies(self, relpath: str) -> bool:
        return relpath.replace("\\", "/").startswith(
            ("kubebrain_tpu/sched/", "kubebrain_tpu/storage/tpu/")
        )

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        def scan(body: list[ast.stmt],
                 func_name: str | None) -> Iterator[tuple[ast.AST, str]]:
            allowed = func_name in _SCAN_DISPATCH_ALLOWED
            for node in walk_same_scope(body):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from scan(node.body, node.name)
                    continue
                if isinstance(node, ast.ClassDef):
                    # methods are where the engine's dispatch code lives —
                    # walk_same_scope stops at the class header, so descend
                    # explicitly (class-level statements get no allowance)
                    yield from scan(node.body, None)
                    continue
                if isinstance(node, ast.Lambda):
                    # a lambda belongs to its enclosing def (the engine
                    # wrappers close over the kernel via lambdas)
                    yield from scan([ast.Expr(value=node.body)], func_name)
                    continue
                if allowed:
                    continue
                # both direct calls and bare references count — wrapping a
                # kernel in vmap/partial outside an assembly point is the
                # same bypass as calling it
                name = None
                if isinstance(node, (ast.Name, ast.Attribute)):
                    name = terminal_name(node)
                if name in _SCAN_DISPATCH_NAMES:
                    where = f" (in {func_name!r})" if func_name else ""
                    yield node, (
                        f"device scan dispatch {name}{where}: kernels may "
                        "only launch from the _dev_mask/_dev_mask_batch "
                        "assembly points"
                    )

        yield from scan(tree.body, None)


#: module-level PRNG roots whose use makes a workload non-replayable
#: (names are matched after alias canonicalization, so ``import random as
#: r`` / ``from random import random`` don't slip through)
_UNSEEDED_RNG_PREFIXES = ("random.", "numpy.random.")
#: constructors that ARE the sanctioned way in — but only with an explicit
#: seed argument (``random.Random()`` falls back to urandom/wall clock)
_SEEDED_RNG_CTORS = {
    "random.Random", "numpy.random.default_rng", "numpy.random.RandomState",
}
_RNG_MODULES = {"random", "numpy", "numpy.random"}


def _rng_alias_maps(tree: ast.Module) -> tuple[dict, dict]:
    """(root alias -> canonical module, from-imported name -> canonical
    dotted name) for the RNG modules — the same aliased-import diligence
    ``_is_time_time`` applies to ``time``."""
    roots: dict[str, str] = {}
    from_names: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in _RNG_MODULES:
                    if a.asname:
                        roots[a.asname] = a.name
                    else:
                        # `import numpy.random` binds the TOP-LEVEL package
                        # name, so the canonical mapping is the identity —
                        # mapping root -> full dotted module would mangle
                        # numpy.array into numpy.random.array
                        root = a.name.split(".", 1)[0]
                        roots.setdefault(root, root)
        elif isinstance(node, ast.ImportFrom) and node.module in _RNG_MODULES:
            for a in node.names:
                from_names[a.asname or a.name] = f"{node.module}.{a.name}"
    return roots, from_names


def _canon_rng_name(name: str, roots: dict, from_names: dict) -> str:
    if name in from_names:
        return from_names[name]
    root, _, rest = name.partition(".")
    if root in roots:
        return roots[root] + ("." + rest if rest else "")
    return name


@register
class ReplayableWorkloadRandomness(Rule):
    """The workload generator's contract is seed ⇒ byte-identical op
    trace (the replay harness's identity, asserted by the determinism
    test AND re-checked on every run). One ``random.random()`` or
    ``time.time()`` on the schedule path silently breaks replays in a way
    no single run can detect — the trace still *looks* plausible. Thread
    the seeded ``random.Random(seed)`` through instead, and use the event
    wheel / monotonic clock for time."""

    rule_id = "KB110"
    summary = ("workload/ must stay replayable: no unseeded randomness "
               "(module-level random.*/np.random.*) and no time.time() — "
               "thread a seeded random.Random; clock via the event wheel")

    def applies(self, relpath: str) -> bool:
        # faults/ carries the same replayability contract: the fault
        # schedule's sha IS the chaos run's replay identity
        p = relpath.replace("\\", "/")
        return (p.startswith("kubebrain_tpu/workload/")
                or p.startswith("kubebrain_tpu/faults/"))

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        roots, from_names = _rng_alias_maps(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _canon_rng_name(dotted_name(node.func), roots, from_names)
            if name in _SEEDED_RNG_CTORS:
                if not node.args and not node.keywords:
                    yield node, (
                        f"{name}() without a seed falls back to wall-clock/"
                        "urandom entropy; pass the spec seed"
                    )
                continue
            if name.startswith(_UNSEEDED_RNG_PREFIXES):
                yield node, (
                    f"module-level PRNG call {name}(): unseeded global "
                    "state breaks seed->trace determinism; use the "
                    "threaded random.Random(seed)"
                )
            elif _is_time_time(node):
                yield node, (
                    "time.time() in workload/: wall-clock reads make the "
                    "schedule non-replayable; use the event wheel "
                    "(simulated time) or time.monotonic() for measurement"
                )


#: device-array producers on the TPU engine's scan/compact path: a host
#: conversion of anything these return (or of a ``*_dev`` mirror column) is
#: a device→host transfer, and outside the named materialization points it
#: is exactly the accidental full-mirror gather that killed the multichip
#: dry run on real traffic
_DEVICE_PRODUCER_NAMES = {
    "_vis_batch", "_vis_batch_q", "_vis_batch_pallas", "_vis_batch_pallas_q",
    "_part_indices_of_mask", "_part_indices_of_mask_sel",
    "_part_survivor_indices", "_victim_part_counts", "_victim_batch",
    "_victim_batch_pallas", "_dev_mask", "_dev_mask_batch",
}
#: numpy host-conversion entry points (device arrays convert implicitly)
_HOST_CONVERTERS = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "np.copy", "numpy.copy",
}
#: the named materialization points allowed to pull device data to host in
#: storage/tpu/ — everything else must go through `_host_pull` (which both
#: blocks correctly and meters the bytes for the transfer-budget tests)
_HOST_TRANSFER_ALLOWED = {
    "_host_pull", "_materialize_visible", "_host_visible",
    "_host_visible_batch", "_pallas_ttl8", "_pull_victim_indices",
    "merge_partitions_incremental",
    # the compaction pipeline's named funnels (docs/compaction.md): the
    # victim-only decode point and the stored-domain mirror-maintenance
    # paths that rebuild sharded device arrays from host columns
    "_compact_victim_rows", "compact_partitions_stored",
    "merge_partitions_stored",
}


def _deviceish_expr(expr: ast.expr) -> str | None:
    """The name making ``expr`` a device-array expression, if any: a
    ``*_dev`` mirror column reference or a call to a device producer."""
    for node in ast.walk(expr):
        if isinstance(node, (ast.Name, ast.Attribute)):
            t = terminal_name(node)
            if t.endswith("_dev"):
                return dotted_name(node) or t
        if isinstance(node, ast.Call):
            t = terminal_name(node.func)
            if t in _DEVICE_PRODUCER_NAMES:
                return t
    return None


@register
class HostTransferOnlyAtMaterializationPoints(Rule):
    """In ``storage/tpu/`` every device→host pull must happen at a named
    materialization point (`_host_pull` and friends): a stray
    ``np.asarray(mirror.keys_dev)`` or ``jax.device_get(mask)`` silently
    re-introduces the full-mirror gather the shard-local scan path exists
    to prevent — O(dataset) bytes over the device link per scan instead of
    O(visible rows) — and dodges the transfer meter the budget tests
    audit."""

    rule_id = "KB111"
    summary = ("storage/tpu/: jax.device_get / host conversion of device "
               "arrays only inside the named materialization points "
               "(_host_pull, _materialize_visible, _host_visible*, "
               "_pallas_ttl8, _pull_victim_indices)")

    def applies(self, relpath: str) -> bool:
        return relpath.replace("\\", "/").startswith("kubebrain_tpu/storage/tpu/")

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        def scan(body: list[ast.stmt],
                 func_name: str | None) -> Iterator[tuple[ast.AST, str]]:
            allowed = func_name in _HOST_TRANSFER_ALLOWED
            for node in walk_same_scope(body):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from scan(node.body, node.name)
                    continue
                if isinstance(node, ast.ClassDef):
                    yield from scan(node.body, None)
                    continue
                if isinstance(node, ast.Lambda):
                    yield from scan([ast.Expr(value=node.body)], func_name)
                    continue
                if allowed or not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                where = f" (in {func_name!r})" if func_name else ""
                if name in ("jax.device_get", "device_get"):
                    yield node, (
                        f"device→host transfer {name}(){where}: only the "
                        "named materialization points may pull device data "
                        "(use _host_pull)"
                    )
                elif name in _HOST_CONVERTERS:
                    dev = next(
                        (d for a in (*node.args, *(kw.value for kw in node.keywords))
                         if (d := _deviceish_expr(a))), None)
                    if dev:
                        yield node, (
                            f"implicit device→host transfer {name}({dev}...)"
                            f"{where}: only the named materialization points "
                            "may pull device data (use _host_pull)"
                        )

        yield from scan(tree.body, None)


#: the decode primitives that turn ENCODED mirror rows back into raw key
#: bytes (storage/tpu/encode.py), and the funnels allowed to call each
#: tier: primitives only inside the Mirror decode funnel, the funnel only
#: inside the named materialization/rebuild paths. Everything else must
#: receive decoded bytes FROM those paths — a stray decode call is an
#: unmetered host materialization of key bytes the compressed-mirror
#: design exists to avoid (it dodges both the visible-row sizing and the
#: transfer-budget accounting).
_DECODE_PRIMITIVES = {"decode_rows", "decode_one"}
_DECODE_PRIMITIVE_FUNNELS = {"decoded_keys", "user_key"}
#: NOTE: ``compact`` itself is deliberately NOT here — since the
#: stored-domain compaction (docs/compaction.md) the only decode the
#: compact pipeline may perform is the victim-only funnel
#: ``_compact_victim_rows``; a whole-partition ``decoded_keys`` call from
#: ``compact`` (the pre-PR-12 shape) is exactly the host decode tax the
#: pipeline removed, and must be flagged.
_DECODE_FUNNEL_CALLERS = {
    "materialize", "flat_arrays", "merge_partitions_incremental",
    "_compact_victim_rows", "_materialize_visible",
}


@register
class DecodeOnlyAtMaterializationFunnels(Rule):
    """Decoded key bytes may only leave the encoded mirror through the
    named funnels: ``KeyEncoding.decode_rows``/``decode_one`` inside
    ``Mirror.decoded_keys``/``user_key``, and ``decoded_keys`` itself only
    from the materialization/rebuild paths (``materialize``,
    ``flat_arrays``, ``merge_partitions_incremental``, and compaction's
    victim-only ``_compact_victim_rows``). A decode call anywhere else
    re-creates the full-width key column on the host outside the
    visible-row/victim-row sizing — the exact cost the prefix-compressed
    mirror (docs/compression.md) and the stored-domain compaction
    (docs/compaction.md) remove. In particular a whole-partition decode
    from ``compact`` itself — the pre-stored-domain shape — is flagged."""

    rule_id = "KB116"
    summary = ("storage/tpu/: encoded-key decode only through the "
               "decoded_keys/user_key funnels, themselves only from the "
               "named materialization/rebuild paths")

    def applies(self, relpath: str) -> bool:
        p = relpath.replace("\\", "/")
        # encode.py IS the implementation being confined; its internal
        # delegation (decode_one → decode_rows) is the primitive itself
        return (p.startswith("kubebrain_tpu/storage/tpu/")
                and not p.endswith("/encode.py"))

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        def scan(body: list[ast.stmt],
                 func_name: str | None) -> Iterator[tuple[ast.AST, str]]:
            for node in walk_same_scope(body):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from scan(node.body, node.name)
                    continue
                if isinstance(node, ast.ClassDef):
                    yield from scan(node.body, None)
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = terminal_name(node.func)
                where = f" (in {func_name!r})" if func_name else ""
                if (name in _DECODE_PRIMITIVES
                        and func_name not in _DECODE_PRIMITIVE_FUNNELS):
                    yield node, (
                        f"raw-key decode {name}(){where}: only the "
                        "Mirror.decoded_keys/user_key funnels may call the "
                        "decode primitives"
                    )
                elif (name == "decoded_keys"
                        and func_name not in _DECODE_FUNNEL_CALLERS
                        and func_name != "decoded_keys"):
                    yield node, (
                        f"decoded_keys(){where}: decoded key bytes only "
                        "leave the mirror through the named materialization"
                        "/rebuild paths (materialize, flat_arrays, "
                        "merge_partitions_incremental, _compact_victim_rows)"
                    )

        yield from scan(tree.body, None)


#: the ONE dispatch point where raw query bounds meet the mirror's compare
#: domain (raw packed chunks or dictionary-encoded rows), plus the host
#: probe path that routes per-key through the same encoding check — every
#: other function must pass bounds through them, never pack its own
_BOUND_DOMAIN_FUNNELS = {"_bound_rows", "_host_visible_batch"}
_RAW_BOUND_PACKERS = {"pack_one"}
_ENCODED_BOUND_HELPERS = {"encode_start_bound", "encode_end_bound",
                          "encode_probe"}


@register
class BoundDomainDispatchOnly(Rule):
    """Raw-domain bound packing (``keyops.pack_one``) and encoded-domain
    bound helpers (``encode_*_bound``/``encode_probe``) are only callable
    inside the engine's domain-dispatch funnels (``_bound_rows``,
    ``_host_visible_batch``) — the naming rule that makes it impossible to
    hand a raw-domain bound to an encoded-mirror compare (or vice versa):
    the only code that sees both domains is the dispatch that checks
    ``mirror.encoding`` first."""

    rule_id = "KB117"
    summary = ("storage/tpu/: bound packing/encoding only inside the "
               "domain-dispatch funnels (_bound_rows, _host_visible_batch) "
               "— kernels must never see a bound from the wrong key domain")

    def applies(self, relpath: str) -> bool:
        p = relpath.replace("\\", "/")
        return (p.startswith("kubebrain_tpu/storage/tpu/")
                and not p.endswith("/encode.py"))

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        def scan(body: list[ast.stmt],
                 func_name: str | None) -> Iterator[tuple[ast.AST, str]]:
            for node in walk_same_scope(body):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from scan(node.body, node.name)
                    continue
                if isinstance(node, ast.ClassDef):
                    yield from scan(node.body, None)
                    continue
                if not isinstance(node, ast.Call):
                    continue
                if func_name in _BOUND_DOMAIN_FUNNELS:
                    continue
                name = terminal_name(node.func)
                where = f" (in {func_name!r})" if func_name else ""
                if name in _RAW_BOUND_PACKERS:
                    yield node, (
                        f"raw-domain bound packing {name}(){where}: pack "
                        "query bounds through _bound_rows so an encoded "
                        "mirror never compares a raw-domain bound"
                    )
                elif name in _ENCODED_BOUND_HELPERS:
                    yield node, (
                        f"encoded-domain bound helper {name}(){where}: "
                        "encode query bounds through _bound_rows/"
                        "_host_visible_batch so a raw mirror never "
                        "compares an encoded-domain bound"
                    )

        yield from scan(tree.body, None)


_REV_TOKENS = {"rev", "revision"}


def _revision_like(expr: ast.expr) -> str | None:
    """The dotted name of the first revision-carrying Name/Attribute inside
    ``expr``, if any ('rev', 'guard_rev', 'request.revision', ...)."""
    for node in ast.walk(expr):
        name = terminal_name(node) if isinstance(node, (ast.Name, ast.Attribute)) else ""
        if name and _REV_TOKENS & set(name.lower().split("_")):
            return dotted_name(node) or name
    return None


#: backend/scanner range-read entry points the service layer must reach
#: through the request scheduler (kubebrain_tpu/sched), never directly —
#: a direct call bypasses admission lanes, coalescing, and overload
#: shedding, so one unthrottled caller can starve the device pipeline.
_SCAN_ENTRY_POINTS = {
    "list_", "list_wire", "list_by_stream", "count", "range_", "range_stream",
    "list_batch", "scan_batch",
}
_SCAN_RECEIVERS = {"backend", "scanner"}
#: backend write entry points — same funnel discipline for the write path
#: (docs/writes.md): service code reaches create/update/delete through the
#: scheduler's write lanes so group commit + admission control apply.
_WRITE_ENTRY_POINTS = {"create", "update", "delete"}
#: ``write_batch`` is the engine/backend group-commit executor itself; the
#: ONLY caller is the scheduler's batch dispatch (sched/scheduler.py) and
#: the backend core — in the service layer it is flagged on ANY receiver,
#: so aliasing the backend (``b = self.backend; b.write_batch(...)``)
#: cannot launder a direct group commit past the admission queue.
_GROUP_COMMIT_ENTRY = "write_batch"


@register
class RangeReadsThroughScheduler(Rule):
    """Service-layer range reads AND writes go through the request
    scheduler (``sched.ensure_scheduler``/the KVService ``limiter``);
    calling the backend/scanner scan or write entry points directly skips
    priority lanes, group commit, and overload protection."""

    rule_id = "KB106"
    summary = ("service-layer code must not call engine scan/write entry "
               "points directly (server/etcd/, endpoint/); use the scheduler")

    def applies(self, relpath: str) -> bool:
        return relpath.replace("\\", "/").startswith(
            ("kubebrain_tpu/server/etcd/", "kubebrain_tpu/endpoint/")
        )

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            receiver = terminal_name(func.value)
            if func.attr == _GROUP_COMMIT_ENTRY:
                yield node, (
                    f"direct group-commit call {receiver}.{func.attr}(); "
                    "write groups form ONLY in the scheduler's dispatch "
                    "(sched.ensure_scheduler create/update/delete)"
                )
                continue
            if func.attr in _SCAN_ENTRY_POINTS:
                if receiver in _SCAN_RECEIVERS:
                    yield node, (
                        f"direct scan call {receiver}.{func.attr}(); range "
                        "reads go through the request scheduler "
                        "(sched.ensure_scheduler)"
                    )
            elif func.attr in _WRITE_ENTRY_POINTS and receiver == "backend":
                yield node, (
                    f"direct write call {receiver}.{func.attr}(); writes go "
                    "through the scheduler's write lanes "
                    "(sched.ensure_scheduler) so group commit and admission "
                    "control apply"
                )


@register
class RevisionFlowsThroughHelpers(Rule):
    """Revisions are opaque monotonic tokens minted by the sequencer; raw
    arithmetic in the etcd surface invents revisions the backend never
    issued. Transformations live in server/service/revision.py helpers."""

    rule_id = "KB105"
    summary = "revision arithmetic in server/etcd/ must use revision.py helpers"

    def applies(self, relpath: str) -> bool:
        return relpath.replace("\\", "/").startswith("kubebrain_tpu/server/etcd/")

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        arith = (ast.Add, ast.Sub, ast.Mult, ast.FloorDiv, ast.Div, ast.Mod)

        def _is_text(n: ast.expr) -> bool:
            # serializing a revision into a bytes/str frame is encoding,
            # not revision arithmetic
            if isinstance(n, ast.Constant) and isinstance(n.value, (str, bytes)):
                return True
            return isinstance(n, ast.BinOp) and (_is_text(n.left) or _is_text(n.right))

        for node in ast.walk(tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, arith):
                if isinstance(node.op, ast.Add) and (_is_text(node.left) or _is_text(node.right)):
                    continue
                name = _revision_like(node.left) or _revision_like(node.right)
                if name:
                    yield node, (
                        f"raw arithmetic on revision value {name!r}; use a "
                        "server/service/revision.py helper"
                    )
            elif isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
                name = _revision_like(node.operand)
                if name:
                    yield node, (
                        f"raw negation of revision value {name!r}; use a "
                        "server/service/revision.py helper"
                    )
            elif isinstance(node, ast.AugAssign) and isinstance(node.op, arith):
                name = _revision_like(node.target)
                if name:
                    yield node, (
                        f"raw in-place arithmetic on revision value {name!r}; "
                        "use a server/service/revision.py helper"
                    )


# --------------------------------------------------------------------- KB118
#: names whose presence in a loop suggests the retry count/window is bounded
_RETRY_BOUND_RE = re.compile(
    r"attempt|retr|tries|deadline|budget|remain|give_up|max_|horizon",
    re.IGNORECASE)
#: names whose presence in a sleep argument suggests jittered backoff
_JITTER_RE = re.compile(r"jitter|random|uniform|backoff|expov|rng",
                        re.IGNORECASE)
_LOCKISH_RE = re.compile(r"lock|mutex|cond", re.IGNORECASE)


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the except body neither re-raises, exits the loop, nor
    captures the exception for delivery — the shape that turns a loop
    into a retry loop. A handler that binds ``as e`` and then USES ``e``
    is delivering the error somewhere (a waiter, a result slot), not
    retrying past it."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return False
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return False  # nested defs run later; be conservative
    if handler.name:
        for node in ast.walk(handler):
            if isinstance(node, ast.Name) and node.id == handler.name \
                    and isinstance(node.ctx, ast.Load):
                return False
    return True


def _loop_names(loop: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in walk_same_scope(getattr(loop, "body", [])):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    # the loop test itself may carry the bound (while attempts < N)
    test = getattr(loop, "test", None)
    if test is not None:
        for node in ast.walk(test):
            if isinstance(node, ast.Name):
                out.add(node.id)
            elif isinstance(node, ast.Attribute):
                out.add(node.attr)
    return out


def _is_while_true(loop: ast.AST) -> bool:
    return (isinstance(loop, ast.While)
            and isinstance(loop.test, ast.Constant)
            and bool(loop.test.value))


def _sleep_calls(body: list[ast.stmt]) -> Iterator[ast.Call]:
    for node in walk_same_scope(body):
        if isinstance(node, ast.Call) and dotted_name(node.func) in (
                "time.sleep", "sleep"):
            yield node


def _sleep_has_jitter(call: ast.Call) -> bool:
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        for node in ast.walk(arg):
            if isinstance(node, (ast.Name, ast.Attribute)):
                if _JITTER_RE.search(terminal_name(node) or ""):
                    return True
            if isinstance(node, ast.Call):
                if _JITTER_RE.search(terminal_name(node.func) or ""):
                    return True
    return False


def _locks_enclosing(tree: ast.Module, line: int) -> list[ast.AST]:
    """With-blocks whose context expression names a lock and whose span
    covers ``line`` (lexical only — the transitive case is KB112's)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        end = getattr(node, "end_lineno", 0) or 0
        if not (node.lineno <= line <= end):
            continue
        for item in node.items:
            name = dotted_name(item.context_expr) or terminal_name(
                item.context_expr)
            if isinstance(item.context_expr, ast.Call):
                name = dotted_name(item.context_expr.func)
            if name and _LOCKISH_RE.search(name.rsplit(".", 1)[-1]):
                out.append(node)
    return out


@register
class RetryLoopHygiene(Rule):
    """Serving-path retry loops must be BOUNDED, BACKED OFF WITH JITTER,
    and never sleep while holding a lock (docs/faults.md). The chaos
    harness makes every engine call failable — an unbounded `while True`
    retry with a constant sleep turns one injected fault window into a
    convoy: every retrier wakes at the same instant forever, and a lock
    held across the sleep wedges every other thread for the full backoff.
    KB112's interprocedural lock stacks cover the TRANSITIVE
    sleep-under-lock case; this rule pins the lexical shapes:

    - ``while True`` + an exception handler that swallows-and-retries,
      with no attempt/deadline bound anywhere in the loop;
    - ``time.sleep`` inside a retry loop with no jitter term in the
      argument expression;
    - ``time.sleep`` inside a retry loop lexically under a ``with *lock``.
    """

    rule_id = "KB118"
    summary = ("serving-path retry loops: bounded attempts, jittered "
               "backoff, no time.sleep under a lock")

    _PACKAGES = ("kubebrain_tpu/backend/", "kubebrain_tpu/storage/",
                 "kubebrain_tpu/server/", "kubebrain_tpu/sched/",
                 "kubebrain_tpu/endpoint/", "kubebrain_tpu/lease/",
                 "kubebrain_tpu/faults/", "kubebrain_tpu/client.py")

    def applies(self, relpath: str) -> bool:
        p = relpath.replace("\\", "/")
        return any(p.startswith(pkg) for pkg in self._PACKAGES)

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        for loop in ast.walk(tree):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            swallowing = [
                h for node in walk_same_scope(loop.body)
                if isinstance(node, ast.Try)
                for h in node.handlers if _handler_swallows(h)
            ]
            if not swallowing:
                continue  # not a retry loop
            names = _loop_names(loop)
            bounded = (isinstance(loop, ast.For)  # for i in range(N): bounded
                       or any(_RETRY_BOUND_RE.search(n) for n in names)
                       or not _is_while_true(loop))
            if not bounded:
                yield loop, (
                    "unbounded `while True` retry loop (exception swallowed "
                    "and retried with no attempt cap or deadline); bound it "
                    "or escalate after K failures"
                )
            for call in _sleep_calls(loop.body):
                if _locks_enclosing(tree, call.lineno):
                    yield call, (
                        "time.sleep in a retry loop while holding a lock: "
                        "the backoff wedges every other thread on that lock "
                        "(transitive case: KB112)"
                    )
                elif not _sleep_has_jitter(call):
                    yield call, (
                        "retry backoff without jitter: a fleet of retriers "
                        "sleeping a constant re-collides forever; multiply "
                        "by random.uniform(0.5, 1.5) or similar"
                    )


#: the watch fan-out mask kernels (ops.fanout.fanout_mask* — prefix match,
#: E-major range, W-major range). Referencing one outside the two dispatch
#: funnels forks the packing discipline: a stray call site can silently
#: disagree on bound canonicalization (NUL single-key bounds), packed
#: width (the auto-grown table width), W/E padding, or the wat-mesh
#: sharding — the same drift KB109 fences for the scan kernels.
_FANOUT_MASK_PREFIX = "fanout_mask"
#: modules allowed to reference them: the legacy per-batch funnel (which
#: also defines them), the block-batched dispatch funnel, and the fused
#: multichip data-plane step (its own assembly point — the kernel runs
#: inside one shard_map'd step over the part x wat mesh)
_FANOUT_MASK_ALLOWED = (
    "kubebrain_tpu/ops/fanout.py",
    "kubebrain_tpu/fanout/dispatch.py",
    "kubebrain_tpu/parallel/step.py",
)


@register
class FanoutMaskOnlyInDispatchFunnels(Rule):
    """The fan-out mask kernels may only be referenced from the two
    dispatch funnels (`ops/fanout.py`, `fanout/dispatch.py`) — everything
    above (matcher, hub, backend) consumes masks or compacted index pairs,
    never launches the kernel itself (docs/watch.md). Imports count: an
    alias smuggled into another module is the same bypass as a call."""

    rule_id = "KB127"
    summary = ("fanout_mask* kernels may only be referenced from the "
               "dispatch funnels (ops/fanout.py, fanout/dispatch.py)")

    def applies(self, relpath: str) -> bool:
        p = relpath.replace("\\", "/")
        return p.startswith("kubebrain_tpu/") and p not in _FANOUT_MASK_ALLOWED

    def check(self, tree: ast.Module, src: str) -> Iterable[tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            name = None
            if isinstance(node, (ast.Name, ast.Attribute)):
                name = terminal_name(node)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name.startswith(_FANOUT_MASK_PREFIX):
                        name = a.name
                        break
            if name and name.startswith(_FANOUT_MASK_PREFIX):
                yield node, (
                    f"fan-out mask kernel reference {name!r}: the kernels "
                    "launch only from the dispatch funnels (ops/fanout.py, "
                    "fanout/dispatch.py); consume the matcher's masks or "
                    "compacted pairs instead"
                )
