"""SARIF 2.1.0 emission for GitHub code scanning.

One run, one driver ("kblint"), one result per finding. Rule metadata is
assembled from the syntactic registry plus the deep-tier catalogue so the
code-scanning UI shows the invariant text, not just an opaque ID.
Baselined findings are emitted with ``"baselineState": "unchanged"`` —
they stay visible in the scan without failing it, matching the CLI's
exit-code behavior.
"""

from __future__ import annotations

import json
from typing import Any, Iterable

from .core import Finding

_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
           "Schemata/sarif-schema-2.1.0.json")


def _rule_catalogue() -> dict[str, str]:
    from .core import RULES
    from .contexts import DEEP_RULES
    cat = {rid: rule.summary for rid, rule in RULES.items()}
    cat.update(DEEP_RULES)
    cat.setdefault("KB000", "file is unreadable or does not parse")
    return cat


def to_sarif(findings: Iterable[Finding],
             baselined: Iterable[Finding] = ()) -> dict[str, Any]:
    cat = _rule_catalogue()
    used: dict[str, int] = {}
    results: list[dict[str, Any]] = []

    def emit(f: Finding, state: str | None) -> None:
        idx = used.setdefault(f.rule_id, len(used))
        res: dict[str, Any] = {
            "ruleId": f.rule_id,
            "ruleIndex": idx,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/"),
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": max(f.col + 1, 1),
                    },
                }
            }],
        }
        if state is not None:
            res["baselineState"] = state
        results.append(res)

    for f in findings:
        emit(f, None)
    for f in baselined:
        emit(f, "unchanged")

    rules = [
        {
            "id": rid,
            "shortDescription": {"text": cat.get(rid, rid)},
            "helpUri": "docs/static_analysis.md",
        }
        for rid, _ in sorted(used.items(), key=lambda kv: kv[1])
    ]
    return {
        "$schema": _SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "kblint",
                    "informationUri":
                        "https://github.com/kubewharf/kubebrain",
                    "rules": rules,
                }
            },
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def write_sarif(path: str, findings: Iterable[Finding],
                baselined: Iterable[Finding] = ()) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_sarif(findings, baselined), fh, indent=1)
        fh.write("\n")
