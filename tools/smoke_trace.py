#!/usr/bin/env python
"""Observability smoke check (tools/ci.sh + check.yml): start a real server,
issue one Range through the client library, and assert the trace pipeline is
live end to end — /debug/traces holds a multi-stage Range span and
kb_rpc_stage_seconds shows queue-wait + device-compute on /metrics.

Exit 0 on success; prints the failing surface otherwise.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main() -> int:
    sys.path.insert(0, REPO)
    from kubebrain_tpu.client import EtcdCompatClient

    client_port, info_port = free_port(), free_port()
    server = subprocess.Popen(
        [sys.executable, "-m", "kubebrain_tpu.cli", "--single-node",
         "--storage", "memkv", "--host", "127.0.0.1",
         "--client-port", str(client_port),
         "--peer-port", str(free_port()), "--info-port", str(info_port),
         "--jax-platform", "cpu"],
        cwd=REPO, stderr=subprocess.DEVNULL,
    )
    try:
        # fresh channel per probe: a channel opened before the server binds
        # accrues reconnect backoff and can stay TRANSIENT_FAILURE long
        # after the port is live (the test_kvrpc boot-probe lesson)
        c = None
        deadline = time.time() + 60
        while time.time() < deadline:
            probe = EtcdCompatClient(f"127.0.0.1:{client_port}")
            try:
                probe.count(b"/x", b"/y")
                c = probe
                break
            except Exception:
                probe.close()
                time.sleep(0.3)
        if c is None:
            print("FAIL: server never served", file=sys.stderr)
            return 1
        ok, _rev = c.create(b"/registry/pods/default/smoke-1", b"v1")
        assert ok, "create failed"
        kvs, _ = c.list(b"/registry/pods/", b"/registry/pods0")
        assert len(kvs) == 1, kvs
        c.close()

        with urllib.request.urlopen(
            f"http://127.0.0.1:{info_port}/debug/traces", timeout=10
        ) as resp:
            snap = json.loads(resp.read())
        ranges = [t for t in snap["traces"] if t["name"] == "etcd.KV/Range"]
        if not ranges:
            print(f"FAIL: no Range span in /debug/traces: {snap}", file=sys.stderr)
            return 1
        stages = {s["stage"] for s in ranges[-1]["stages"]}
        if len(stages) < 5 or not {"queue_wait", "device_compute"} <= stages:
            print(f"FAIL: Range span stages incomplete: {sorted(stages)}",
                  file=sys.stderr)
            return 1

        with urllib.request.urlopen(
            f"http://127.0.0.1:{info_port}/metrics", timeout=10
        ) as resp:
            metrics = resp.read().decode()
        for needle in ("kb_rpc_stage_seconds_bucket",
                       'stage="queue_wait"', 'stage="device_compute"'):
            if needle not in metrics:
                print(f"FAIL: {needle!r} missing from /metrics", file=sys.stderr)
                return 1
        print(f"OK: trace smoke — span stages {sorted(stages)}, "
              "kb_rpc_stage_seconds populated")
        return 0
    finally:
        server.terminate()
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()


if __name__ == "__main__":
    sys.exit(main())
