"""Pallas scan-kernel tile-size sweep on the live chip.

Builds the 20M-row bench dataset ONCE (saved to /tmp as .npy), then times
``scan_mask_pallas`` for each KB_PALLAS_TILE in a fresh subprocess (the
tile is a trace-time constant). Prints one JSON line per tile.

Usage:
  python tools/tile_sweep.py build          # build + save dataset
  python tools/tile_sweep.py run <tile>     # time one tile size (subprocess)
  python tools/tile_sweep.py sweep          # build if needed, run all tiles
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

DATA = "/tmp/kb_tile_sweep"
TILES = (512, 1024, 2048, 4096, 8192, 16384)
N_KEYS = int(os.environ.get("KB_BENCH_KEYS", 200_000))
REVS = int(os.environ.get("KB_BENCH_REVS", 100))


def build() -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build_dataset, pack_bound

    chunks, rh, rl, tomb = build_dataset(N_KEYS, REVS)
    os.makedirs(DATA, exist_ok=True)
    np.save(f"{DATA}/chunks.npy", chunks)
    np.save(f"{DATA}/rh.npy", rh)
    np.save(f"{DATA}/rl.npy", rl)
    np.save(f"{DATA}/tomb.npy", tomb)
    np.save(f"{DATA}/start.npy", pack_bound(b"/registry/pods/"))
    np.save(f"{DATA}/end.npy", pack_bound(b"/registry/pods0"))
    print(f"[sweep] dataset saved: {len(chunks)} rows", file=sys.stderr)


def run(tile: int) -> None:
    os.environ["KB_PALLAS_TILE"] = str(tile)
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import jax
    import jax.numpy as jnp

    from kubebrain_tpu.ops import scan_pallas as sp

    chunks = np.load(f"{DATA}/chunks.npy")
    rh = np.load(f"{DATA}/rh.npy")
    rl = np.load(f"{DATA}/rl.npy")
    tomb = np.load(f"{DATA}/tomb.npy")
    start = np.load(f"{DATA}/start.npy")
    end = np.load(f"{DATA}/end.npy")
    n = len(chunks)
    read_rev = np.uint64(n * 3 // 4)

    revs_u64 = (rh.astype(np.uint64) << np.uint64(32)) | rl.astype(np.uint64)
    keys_t, rh31, rl31, tomb8, n_real = sp.prepare_blocks(chunks, revs_u64, tomb)
    qhi31, qlo31 = sp.split_revs31(np.array([read_rev], dtype=np.uint64))
    s = sp.pack_bound_flipped(start)
    e = sp.pack_bound_flipped(end)

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    d = [jax.device_put(jnp.asarray(x), dev) for x in (keys_t, rh31, rl31, tomb8)]
    s_d, e_d = jax.device_put(jnp.asarray(s), dev), jax.device_put(jnp.asarray(e), dev)

    @jax.jit
    def step(kt, a, b, t8, sb, eb):
        m = sp.scan_mask_pallas(kt, a, b, t8, np.int32(n_real), sb, eb,
                                np.int32(0), np.int32(qhi31[0]), np.int32(qlo31[0]),
                                interpret=not on_tpu)
        return jnp.sum(m, dtype=jnp.int32)

    t0 = time.time()
    visible = int(step(*d, s_d, e_d))
    compile_s = time.time() - t0
    lat = []
    for _ in range(7):
        t0 = time.time()
        int(step(*d, s_d, e_d))
        lat.append(time.time() - t0)
    p50 = sorted(lat)[len(lat) // 2]
    best = min(lat)
    print(json.dumps({
        "tile": tile, "rows": n, "visible": visible,
        "p50_ms": round(p50 * 1e3, 2), "best_ms": round(best * 1e3, 2),
        "rows_per_sec": round(n / p50), "compile_s": round(compile_s, 1),
        "device": str(dev),
    }), flush=True)


def sweep() -> None:
    if not os.path.exists(f"{DATA}/chunks.npy"):
        subprocess.run([sys.executable, __file__, "build"], check=True)
    for tile in TILES:
        try:
            r = subprocess.run([sys.executable, __file__, "run", str(tile)],
                               capture_output=True, text=True, timeout=1200)
        except subprocess.TimeoutExpired:
            # a wedged tunnel must not lose the remaining tiles' results
            print(f'{{"tile": {tile}, "error": "timeout (tunnel wedged?)"}}', flush=True)
            continue
        out = r.stdout.strip()
        print(out if out else f'{{"tile": {tile}, "error": {json.dumps(r.stderr[-500:])}}}',
              flush=True)


if __name__ == "__main__":
    cmd = sys.argv[1] if len(sys.argv) > 1 else "sweep"
    if cmd == "build":
        build()
    elif cmd == "run":
        run(int(sys.argv[2]))
    else:
        sweep()
