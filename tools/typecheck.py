"""`make typecheck` entry point.

Runs mypy over the typed core when mypy is installed — CI installs a
PINNED version (MYPY_PIN, mirrored by .github/workflows/check.yml) so the
verdict cannot drift with upstream releases; in containers without it
(this repo must not pip install anything) it degrades to a full-tree
bytecode compilation pass so the target still catches syntax/obvious-name
breakage instead of silently no-opping. Exit 0 = clean under whichever
checker ran.

The typed set: storage/, ops/, server/service (since PR 1), plus the
strict-ish per-package ratchets in mypy.ini for sched/, lease/, replica/,
faults/, workload/, trace/, and tools/kblint (disallow_incomplete_defs +
no_implicit_optional).
"""

from __future__ import annotations

import compileall
import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: the version CI installs (check.yml); keep the two in sync
MYPY_PIN = "1.11.2"
TYPED_PACKAGES = [
    "kubebrain_tpu/storage",
    "kubebrain_tpu/ops",
    "kubebrain_tpu/server/service",
    "kubebrain_tpu/sched",
    "kubebrain_tpu/lease",
    "kubebrain_tpu/replica",
    "kubebrain_tpu/faults",
    "kubebrain_tpu/workload",
    "kubebrain_tpu/trace",
    "tools/kblint",
]


def main() -> int:
    if importlib.util.find_spec("mypy") is not None:
        try:
            import mypy.version
            if mypy.version.__version__ != MYPY_PIN:
                print(f"typecheck: warning: mypy {mypy.version.__version__} "
                      f"!= pinned {MYPY_PIN} (CI installs the pin; local "
                      "verdicts may differ)", file=sys.stderr)
        except Exception:
            pass
        cmd = [sys.executable, "-m", "mypy", "--config-file",
               os.path.join(REPO, "mypy.ini"), *TYPED_PACKAGES]
        print("typecheck: mypy", " ".join(TYPED_PACKAGES))
        return subprocess.run(cmd, cwd=REPO).returncode

    print("typecheck: mypy not installed in this container; "
          "running compileall fallback over the whole tree")
    ok = True
    for pkg in ["kubebrain_tpu", "tools", "tests"]:
        ok &= compileall.compile_dir(
            os.path.join(REPO, pkg), quiet=1, force=False,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
