"""`make typecheck` entry point.

Runs mypy over the typed core (kubebrain_tpu/storage, ops, server/service)
when mypy is installed; in containers without it (this repo must not pip
install anything) it degrades to a full-tree bytecode compilation pass so
the target still catches syntax/obvious-name breakage instead of silently
no-opping. Exit 0 = clean under whichever checker ran.
"""

from __future__ import annotations

import compileall
import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TYPED_PACKAGES = [
    "kubebrain_tpu/storage",
    "kubebrain_tpu/ops",
    "kubebrain_tpu/server/service",
]


def main() -> int:
    if importlib.util.find_spec("mypy") is not None:
        cmd = [sys.executable, "-m", "mypy", "--config-file",
               os.path.join(REPO, "mypy.ini"), *TYPED_PACKAGES]
        print("typecheck: mypy", " ".join(TYPED_PACKAGES))
        return subprocess.run(cmd, cwd=REPO).returncode

    print("typecheck: mypy not installed in this container; "
          "running compileall fallback over the whole tree")
    ok = True
    for pkg in ["kubebrain_tpu", "tools", "tests"]:
        ok &= compileall.compile_dir(
            os.path.join(REPO, pkg), quiet=1, force=False,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
